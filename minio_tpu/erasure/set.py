"""ErasureSet — one erasure stripe of K drives (L3 object semantics).

Behavioral mirror of the reference's erasureObjects
(/root/reference/cmd/erasure-object.go): quorum writes with atomic
rename-into-place, greedy degraded reads with bitrot verification and
on-the-fly reconstruction, versioned deletes with delete markers, and
object healing. Compute (RS encode/decode + bitrot digests) rides the
TPU coder (erasure/coder.py).
"""

from __future__ import annotations

import hashlib
import os
import threading
import uuid
from concurrent.futures import ALL_COMPLETED, FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as _fut_wait
from typing import Callable, Iterator

import numpy as np

from .. import obs
from ..fault import registry as fault_registry
from ..ops.bitrot import DEFAULT_BITROT_ALGO
from ..storage import errors
from ..storage.errors import StorageError
from ..storage.datatypes import (
    ChecksumInfo,
    ErasureInfo,
    FileInfo,
    ObjectPartInfo,
    now_ns,
)
from ..storage.format import INLINE_DATA_THRESHOLD
from ..storage.interface import StorageAPI
from ..utils.hashing import hash_order
from . import bitrot_io, bufpool
from .coder import (
    BLOCK_SIZE,
    ErasureCoder,
    default_ec_family,
    family_stats_add,
)
from .quorum import (
    BucketExists,
    BucketNotFound,
    ObjectNotFound,
    QuorumError,
    VersionNotFound,
    count_none,
    find_file_info_in_quorum,
    object_quorum_from_meta,
    reduce_quorum_errs,
)
from .types import BucketInfo, ObjectInfo

TMP_VOLUME = ".minio.sys/tmp"
DIGEST = bitrot_io.DIGEST_SIZE

# namespace-lock deadline adapts to observed acquisition behaviour
# (qos/dyntimeout.py — the reference's globalOperationTimeout dynamic
# timeout): a contended cluster earns a looser deadline instead of
# spurious quorum errors, relaxing back once healthy. The floor equals
# the historical fixed deadline (30 s): healthy near-zero waits must
# never shrink the deadline below what lock HOLD times need — a holder
# legitimately runs seconds of encode+disk I/O (the reference keeps a
# 5-minute floor on its operation timeout for the same reason).
from ..qos.dyntimeout import DynamicTimeout

NS_LOCK_TIMEOUT = DynamicTimeout(30.0, minimum_s=30.0, name="ns-lock")


def _lock_dyn(mtx, write: bool = True) -> bool:
    """Acquire the namespace lock under the adaptive deadline, feeding the
    wait duration (or the timeout) back into the estimator."""
    import time as _time

    t0 = _time.monotonic()
    ok = (mtx.lock if write else mtx.rlock)(NS_LOCK_TIMEOUT.timeout())
    if ok:
        NS_LOCK_TIMEOUT.log_success(_time.monotonic() - t0)
    else:
        NS_LOCK_TIMEOUT.log_failure()
    return ok
# single source for the internal tag metadata key: the S3 layer stores it,
# the ILM scanner filters on it, this layer round-trips it
TAGS_META_KEY = "x-minio-internal-tags"


def _whole_file_hash(m: "FileInfo", part_number: int):
    """This drive's stored (digest, algorithm) for a part, or None when the
    shard uses the streaming format (reference cmd/bitrot-whole.go: legacy
    shards carry one metadata digest instead of interleaved frames). The
    stored algorithm matters: legacy data may be sha256/blake2b hashed."""
    from ..ops.bitrot import algorithm_from_string

    for c in m.erasure.checksums:
        if c.part_number == part_number and c.hash:
            return c.hash, algorithm_from_string(c.algorithm)
    return None


def _native_plane_enabled(device_active: bool = False) -> bool:
    """Native C++ streaming data plane (native/dataplane.cpp): used for the
    PUT/GET hot path whenever every target drive is local. One GIL-releasing
    pass replaces the per-block Python loop (VERDICT r2: the ~1000x
    kernel-to-server gap lived in this plumbing).

    MINIO_TPU_NATIVE_PLANE: "auto" (default) = take the native pass unless
    a device codec is active for this write (the TPU batching dispatcher is
    the accelerator plane; the native pass is the CPU plane); "1" = always;
    "0" = never.
    """
    mode = os.environ.get("MINIO_TPU_NATIVE_PLANE", "auto")
    if mode == "0":
        return False
    if mode != "1" and device_active:
        return False
    from .. import native

    return native.dataplane_available()

def _repair_windowed_enabled() -> bool:
    """MINIO_TPU_REPAIR_WINDOWED gates the windowed + hedged execution of
    partial-repair plans (degraded GET and heal). "0" keeps the original
    block-serial executor — the A/B baseline the BENCH_r12 wall-clock
    gate measures against; correctness is identical either way."""
    return os.environ.get("MINIO_TPU_REPAIR_WINDOWED", "1") != "0"


# shared shard-read pool: per-block shard reads of ALL in-flight GETs fan
# out here (the reference spawns per-shard goroutines; a bounded pool is
# the python equivalent)
_READ_POOL: ThreadPoolExecutor | None = None
_READ_POOL_LOCK = threading.Lock()


def _read_pool() -> ThreadPoolExecutor:
    global _READ_POOL
    if _READ_POOL is None:
        with _READ_POOL_LOCK:
            if _READ_POOL is None:
                # context-propagating: shard reads publish `storage` spans
                # that must carry the caller's trace request id
                _READ_POOL = obs.ContextPool(
                    max_workers=int(os.environ.get("MINIO_TPU_READ_WORKERS", "32")),
                    thread_name_prefix="shard-read",
                )
    return _READ_POOL


def default_parity_count(drive_count: int) -> int:
    """Default storage-class parity by set width (reference
    internal/config/storageclass defaults)."""
    if drive_count == 1:
        return 0
    if drive_count <= 3:
        return 1
    if drive_count <= 5:
        return 2
    if drive_count <= 7:
        return 3
    return 4


class ErasureSet:
    def __init__(
        self,
        disks: list[StorageAPI],
        default_parity: int | None = None,
        set_index: int = 0,
        pool_index: int = 0,
        ns_lock=None,
    ):
        from ..cluster.locks import NamespaceLock

        if len(disks) < 1:
            raise ValueError("need at least one drive")
        self.disks = list(disks)
        self.n = len(disks)
        self.set_index = set_index
        self.pool_index = pool_index
        self.default_parity = (
            default_parity if default_parity is not None else default_parity_count(self.n)
        )
        self.ns = ns_lock if ns_lock is not None else NamespaceLock()
        self._pool = obs.ContextPool(max_workers=max(4, self.n))
        self._coders: dict[tuple[int, int, str], ErasureCoder] = {}
        # read-path degradation hook (MRF heal-on-read, reference cmd/mrf.go)
        self.on_degraded = None
        self._bucket_cache: dict[str, float] = {}
        # quorum-coherent caching layer (cache/): FileInfo + hot-object
        # tiers; every mutation below invalidates through its choke point
        from ..cache import SetCache

        self.cache = SetCache(self)

    # -- helpers -----------------------------------------------------------

    def coder(self, d: int, p: int, family: str = "reedsolomon") -> ErasureCoder:
        key = (d, p, family)
        if key not in self._coders:
            self._coders[key] = ErasureCoder(d, p, family=family)
        return self._coders[key]

    def coder_for(self, fi: FileInfo) -> ErasureCoder:
        """Codec for a STORED object: every decode/heal path dispatches
        on the family recorded in its xl.meta, so objects written under
        different MINIO_TPU_EC_FAMILY settings coexist on one set. An
        unknown family string raises the typed UnknownErasureFamily
        (never a misread frame)."""
        family = bitrot_io.check_family(
            fi.erasure.algorithm or bitrot_io.FAMILY_RS
        )
        return self.coder(
            fi.erasure.data_blocks, fi.erasure.parity_blocks, family
        )

    def _hedge_budget_s(self) -> float | None:
        """Straggler budget for hedged shard reads, or None when hedging
        is off. EWMA-derived: a multiple of the MEDIAN per-drive smoothed
        latency (HealthCheckedDisk accounting), floored so a cold/fast
        cluster doesn't hedge on noise. The median keeps one straggling
        drive from inflating its own budget."""
        if os.environ.get("MINIO_TPU_HEDGE", "1") == "0":
            return None
        # malformed tuning falls back to defaults: a chaos-knob typo must
        # not take down the GET path
        try:
            floor = float(os.environ.get("MINIO_TPU_HEDGE_MIN_MS", "50")) / 1e3
        except ValueError:
            floor = 0.05
        try:
            mult = float(os.environ.get("MINIO_TPU_HEDGE_MULT", "4"))
        except ValueError:
            mult = 4.0
        ews = sorted(
            e for e in (
                getattr(d, "ewma_latency", lambda: 0.0)() for d in self.disks
            ) if e > 0.0
        )
        if not ews:
            return floor
        return max(floor, mult * ews[len(ews) // 2])

    def _parallel(self, fn: Callable[[StorageAPI], object]) -> list:
        """Run fn on every drive concurrently; returns [(result|None, err|None)]."""

        def run(disk):
            try:
                return fn(disk), None
            except Exception as e:  # noqa: BLE001 — drive faults become errors
                return None, e

        return list(self._pool.map(run, self.disks))

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        res = self._parallel(lambda d: d.make_vol(bucket))
        errs = [e for _, e in res]
        if all(isinstance(e, errors.VolumeExists) for e in errs if e is not None) and any(
            e is not None for e in errs
        ):
            if count_none(errs) == 0:
                raise BucketExists(bucket)
        reduce_quorum_errs(errs, self.n // 2 + 1, ignored=(errors.VolumeExists,))

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._bucket_cache.pop(bucket, None)
        self.cache.invalidate_bucket(bucket)
        res = self._parallel(lambda d: d.delete_vol(bucket, force=force))
        errs = [e for _, e in res]
        for e in errs:
            if isinstance(e, errors.VolumeNotEmpty):
                from .quorum import BucketNotEmpty

                raise BucketNotEmpty(bucket)
        reduce_quorum_errs(errs, self.n // 2 + 1, ignored=(errors.VolumeNotFound,))

    _BUCKET_CACHE_TTL = 30.0

    def bucket_exists(self, bucket: str) -> bool:
        # read-quorum semantics: half the drives answering is enough to
        # know the bucket exists (writes still enforce write quorum).
        # Positive answers cache briefly so the hot PUT path doesn't pay a
        # stat fan-out per request (negatives never cache: another node may
        # have just created the bucket).
        import time as _time

        hit = self._bucket_cache.get(bucket)
        if hit is not None and _time.monotonic() - hit < self._BUCKET_CACHE_TTL:
            return True
        res = self._parallel(lambda d: d.stat_vol(bucket))
        ok = count_none([e for _, e in res]) >= max(self.n // 2, 1)
        if ok:
            self._bucket_cache[bucket] = _time.monotonic()
        return ok

    def list_buckets(self) -> list[BucketInfo]:
        for disk, (vols, err) in zip(self.disks, self._parallel(lambda d: d.list_vols())):
            if err is None:
                return [
                    BucketInfo(v.name, v.created)
                    for v in vols
                    if not v.name.startswith(".minio.sys")
                ]
        return []

    # -- metadata reads ----------------------------------------------------

    def _read_all_fileinfo(
        self, bucket: str, obj: str, version_id: str, read_data: bool = False
    ) -> tuple[list[FileInfo | None], list[Exception | None]]:
        res = self._parallel(
            lambda d: d.read_version(bucket, obj, version_id, read_data=read_data)
        )
        return [r for r, _ in res], [e for _, e in res]

    def _quorum_fileinfo(
        self, bucket: str, obj: str, version_id: str, read_data: bool = False
    ) -> tuple[FileInfo, list[FileInfo | None], int, int]:
        metas, errs = self._read_all_fileinfo(bucket, obj, version_id, read_data)
        read_q, write_q = object_quorum_from_meta(metas, errs, self.n, self.default_parity)
        reduce_quorum_errs(errs, read_q)
        fi = find_file_info_in_quorum(metas, read_q)
        return fi, metas, read_q, write_q

    def _cached_fileinfo(
        self, bucket: str, obj: str, version_id: str
    ) -> tuple[FileInfo, list[FileInfo | None]]:
        """Read-path quorum metadata via the FileInfo cache: hot keys skip
        the N-drive fan-out; concurrent misses singleflight one quorum
        read (read_data=True so GET and HEAD share one entry). Mutation
        paths keep calling ``_quorum_fileinfo`` directly — they read
        under the write lock and must see authoritative state."""

        def load():
            fi, metas, _, _ = self._quorum_fileinfo(
                bucket, obj, version_id, read_data=True
            )
            return fi, metas

        return self.cache.fileinfo(bucket, obj, version_id, load)

    # -- put ---------------------------------------------------------------

    def put_object(
        self,
        bucket: str,
        obj: str,
        data: bytes,
        user_defined: dict[str, str] | None = None,
        version_id: str | None = None,
        versioned: bool = False,
        parity: int | None = None,
        distribution: list[int] | None = None,
        allow_inline: bool = True,
        check_precond=None,
        family: str | None = None,
    ) -> ObjectInfo:
        """distribution/allow_inline overrides serve the multipart plane:
        all parts of an upload must share the final object's shard layout
        and be rename-able files (never inline). check_precond(current
        ObjectInfo | None) runs UNDER the namespace write lock — the
        conditional-write hook (PUT If-Match / If-None-Match, reference
        checkPreconditionsPUT) with no TOCTOU window. ``family`` picks
        the erasure code family (per-storage-class mapping in the S3
        layer); None uses MINIO_TPU_EC_FAMILY."""
        if not self.bucket_exists(bucket) and not bucket.startswith(".minio.sys"):
            raise BucketNotFound(bucket)
        with obs.span(
            obs.TYPE_INTERNAL, "erasure.put_object", bucket=bucket, object=obj
        ):
            mtx = self.ns.new(bucket, obj)
            if not _lock_dyn(mtx, write=True):
                raise QuorumError(f"namespace write lock timeout on {bucket}/{obj}")
            try:
                if check_precond is not None:
                    try:
                        fi, _, _, _ = self._quorum_fileinfo(
                            bucket, obj, "", read_data=False
                        )
                        cur = None if fi.deleted else self._to_object_info(
                            bucket, obj, fi
                        )
                    except (ObjectNotFound, VersionNotFound):
                        cur = None
                    check_precond(cur)  # raises to abort before any write
                # active refresh with loss abort: a partitioned holder must
                # stop writing once the cluster no longer holds its lock
                # (reference internal/dsync/drwmutex.go:340 refreshLock).
                # Only long-running writes need it — a refresher thread per
                # millisecond PUT would be pure overhead against the 120 s
                # TTL.
                long_running = not isinstance(data, (bytes, bytearray, memoryview)) \
                    or len(data) > (8 << 20)
                if long_running:
                    mtx.start_refresher(write=True)
                oi = self._put_object_locked(
                    bucket, obj, data, user_defined, version_id, versioned,
                    parity, distribution, allow_inline, lock=mtx,
                    family=family,
                )
            finally:
                mtx.unlock()
            # write-through invalidation AFTER the lock releases but
            # BEFORE the PUT returns: the cross-node broadcast (seconds
            # on a blackholed peer) must never inflate lock hold time,
            # and a reader overlapping this window may legitimately
            # serve the pre-overwrite version — the PUT hasn't returned.
            # Loaders racing this are rejected by the cache's
            # invalidation-sequence guard.
            self.cache.invalidate_object(bucket, obj)
            return oi

    def _put_object_locked(
        self,
        bucket: str,
        obj: str,
        data: bytes,
        user_defined: dict[str, str] | None,
        version_id: str | None,
        versioned: bool,
        parity: int | None,
        distribution: list[int] | None,
        allow_inline: bool,
        lock=None,
        family: str | None = None,
    ) -> ObjectInfo:
        family = family or default_ec_family()
        if not isinstance(data, (bytes, bytearray, memoryview)):
            return self._put_object_streaming(
                bucket, obj, data, user_defined, version_id, versioned,
                parity, distribution, lock=lock, family=family,
            )
        p = self.default_parity if parity is None else parity
        d = self.n - p
        if (
            len(data) > INLINE_DATA_THRESHOLD
            and family == bitrot_io.FAMILY_RS
            and _native_plane_enabled(self.coder(d, p).device_active)
            and all(dk.local_path(TMP_VOLUME, "x") is not None for dk in self.disks)
        ):
            # large buffered bodies (signed-payload PUTs) also take the
            # native C++ pass; small ones keep the inline fast path.
            # (The native plane speaks the single-frame reedsolomon
            # format only; other families stream through the coder.)
            return self._put_object_streaming(
                bucket, obj, iter([data]), user_defined, version_id, versioned,
                parity, distribution, lock=lock, family=family,
            )
        write_q = d + 1 if d == p else d

        fi = FileInfo(volume=bucket, name=obj)
        fi.version_id = version_id if version_id is not None else (
            str(uuid.uuid4()) if versioned else ""
        )
        fi.mod_time = now_ns()
        fi.size = len(data)
        fi.metadata = dict(user_defined or {})
        etag = hashlib.md5(data).hexdigest()
        fi.metadata.setdefault("etag", etag)
        fi.erasure = ErasureInfo(
            algorithm=family,
            data_blocks=d,
            parity_blocks=p,
            block_size=BLOCK_SIZE,
            distribution=distribution or hash_order(f"{bucket}/{obj}", self.n),
            checksums=[ChecksumInfo(1, DEFAULT_BITROT_ALGO.string)],
        )
        fi.parts = [ObjectPartInfo(1, len(data), len(data), fi.mod_time, etag)]

        encoded = self.coder(d, p, family).encode_part(data)
        if lock is not None and lock.lost:
            raise QuorumError(f"write lock on {bucket}/{obj} lost; aborting")
        inline = allow_inline and len(data) <= INLINE_DATA_THRESHOLD
        if not inline:
            fi.data_dir = str(uuid.uuid4())

        tmp_id = str(uuid.uuid4())

        def write_one(i: int, disk: StorageAPI):
            shard_idx = fi.erasure.distribution[i] - 1
            dfi = FileInfo.from_dict(fi.to_dict())
            dfi.volume, dfi.name = bucket, obj
            dfi.erasure.index = shard_idx + 1
            if inline:
                dfi.inline_data = encoded.shard_files[shard_idx]
                disk.write_metadata(bucket, obj, dfi)
            else:
                stage = f"{tmp_id}/{fi.data_dir}/part.1"
                disk.create_file(TMP_VOLUME, stage, encoded.shard_files[shard_idx])
                disk.rename_data(TMP_VOLUME, tmp_id, dfi, bucket, obj)

        futs = [
            self._pool.submit(write_one, i, disk) for i, disk in enumerate(self.disks)
        ]
        errs: list[Exception | None] = []
        for f in futs:
            try:
                f.result()
                errs.append(None)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        try:
            reduce_quorum_errs(errs, write_q)
        except Exception:
            # quorum failed: undo partial writes so no durable garbage
            # remains (reference deletes the partial object on quorum loss)
            for disk, err in zip(self.disks, errs):
                try:
                    if err is None:
                        disk.delete_version(bucket, obj, fi)
                    disk.delete(TMP_VOLUME, tmp_id, recursive=True)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            raise
        # quorum passed, but a minority drive may have staged its shard
        # and then failed before rename_data swept the staging dir — the
        # staged bytes must not outlive the PUT (the streaming path
        # sweeps the same way after its commit)
        self._sweep_staging(
            tmp_id, (d for d, e in zip(self.disks, errs) if e is not None)
        )
        return self._to_object_info(bucket, obj, fi)

    def _put_object_streaming(
        self,
        bucket: str,
        obj: str,
        reader,
        user_defined: dict[str, str] | None,
        version_id: str | None,
        versioned: bool,
        parity: int | None,
        distribution: list[int] | None,
        lock=None,
        family: str | None = None,
    ) -> ObjectInfo:
        """Bounded-memory PUT: encode batches of stripe blocks as they
        arrive and append shard-file chunks to each drive's staged part
        file — a part is never fully resident (the reference streams
        block-by-block through a ring buffer,
        /root/reference/cmd/bitrot-streaming.go:108-133). Never inlines.
        """
        family = family or default_ec_family()
        p = self.default_parity if parity is None else parity
        d = self.n - p
        write_q = d + 1 if d == p else d

        fi = FileInfo(volume=bucket, name=obj)
        fi.version_id = version_id if version_id is not None else (
            str(uuid.uuid4()) if versioned else ""
        )
        fi.mod_time = now_ns()
        fi.metadata = dict(user_defined or {})
        fi.erasure = ErasureInfo(
            algorithm=family,
            data_blocks=d,
            parity_blocks=p,
            block_size=BLOCK_SIZE,
            distribution=distribution or hash_order(f"{bucket}/{obj}", self.n),
            checksums=[ChecksumInfo(1, DEFAULT_BITROT_ALGO.string)],
        )
        fi.data_dir = str(uuid.uuid4())
        tmp_id = str(uuid.uuid4())
        stage = f"{tmp_id}/{fi.data_dir}/part.1"
        coder = self.coder(d, p, family)
        md5 = hashlib.md5()
        size = 0
        # a drive that fails once stops receiving appends (its staged file
        # would be torn); quorum judged at the end
        errs: list[Exception | None] = [None] * self.n

        def drive_op(i: int, fn, *args):
            if errs[i] is None:
                try:
                    fn(*args)
                except Exception as e:  # noqa: BLE001
                    errs[i] = e

        futs = [
            self._pool.submit(drive_op, i, disk.create_file, TMP_VOLUME, stage, b"")
            for i, disk in enumerate(self.disks)
        ]
        for f in futs:
            f.result()
        renamed = False  # whether any rename_data may have landed
        stream_cap = int(os.environ.get("MINIO_TPU_STREAM_BATCH_MB", "64")) << 20
        # native C++ single-pass plane when every drive is local + healthy
        # (reedsolomon framing only — sub-packetized families stream
        # through the coder's python/device path)
        native_paths: list[str] | None = None
        if family == bitrot_io.FAMILY_RS and _native_plane_enabled(
            coder.device_active
        ) and all(
            e is None for e in errs
        ):
            native_paths = [""] * self.n
            for i, disk in enumerate(self.disks):
                lp = disk.local_path(TMP_VOLUME, stage)
                if lp is None:
                    native_paths = None
                    break
                native_paths[fi.erasure.distribution[i] - 1] = lp
        try:
            if native_paths is not None:
                etag, size = self._stream_native(
                    native_paths, reader, coder, fi, errs, write_q, lock,
                    bucket, obj,
                )
            else:
                # zero-copy plane: reader chunks accumulate straight into
                # pooled arenas in dispatcher geometry; shard appends are
                # writev vectors of encode-output views. Each batch's
                # arena is released only after md5 + every drive append
                # completed (drive_op futures joined) — a mid-PUT drive
                # failure can therefore never recycle a referenced arena.
                # process-global site counters: the delta is this PUT's
                # copies plus any concurrent traffic — an attribution
                # signal for the obs stream, not an exact per-request bill
                copies0 = bufpool.copies_snapshot() if obs.active() else None
                batch = None
                try:
                    for batch in coder.iter_encode_zc(
                        reader, max_batch_bytes=stream_cap
                    ):
                        if lock is not None and lock.lost:
                            raise QuorumError(
                                f"write lock on {bucket}/{obj} lost mid-stream;"
                                " aborting"
                            )
                        md5.update(batch.raw)
                        size += len(batch.raw)
                        futs = []
                        for i, disk in enumerate(self.disks):
                            shard_idx = fi.erasure.distribution[i] - 1
                            futs.append(self._pool.submit(
                                drive_op, i, disk.append_file, TMP_VOLUME, stage,
                                batch.shard_vecs[shard_idx],
                            ))
                        for f in futs:
                            f.result()
                        batch.release()
                        batch = None
                        if sum(e is None for e in errs) < write_q:
                            raise QuorumError("write quorum lost mid-stream")
                finally:
                    if batch is not None:
                        batch.release()
                etag = md5.hexdigest()
                if copies0 is not None:
                    import time as _time

                    copies1 = bufpool.copies_snapshot()
                    obs.publish({
                        "time": _time.time(),
                        "type": obs.TYPE_TPU,
                        "name": "copy.site",
                        "node": obs.trace.NODE,
                        "bytes": size,
                        "zerocopy": bufpool.zerocopy_enabled(),
                        "sites": {
                            s: copies1[s] - copies0.get(s, 0)
                            for s in copies1
                            if copies1[s] - copies0.get(s, 0)
                        },
                    })

            fi.size = size
            fi.metadata.setdefault("etag", etag)
            fi.parts = [ObjectPartInfo(1, size, size, fi.mod_time, etag)]

            def commit_one(i: int, disk: StorageAPI):
                shard_idx = fi.erasure.distribution[i] - 1
                dfi = FileInfo.from_dict(fi.to_dict())
                dfi.volume, dfi.name = bucket, obj
                dfi.erasure.index = shard_idx + 1
                disk.rename_data(TMP_VOLUME, tmp_id, dfi, bucket, obj)

            if lock is not None and lock.lost:
                raise QuorumError(
                    f"write lock on {bucket}/{obj} lost before commit; aborting"
                )
            renamed = True
            futs = [
                self._pool.submit(drive_op, i, commit_one, i, disk)
                for i, disk in enumerate(self.disks)
            ]
            for f in futs:
                f.result()
            reduce_quorum_errs(errs, write_q)
        except Exception:
            for disk, err in zip(self.disks, errs):
                try:
                    # only roll back committed renames: a failure BEFORE the
                    # rename phase must never touch the pre-existing object
                    # (deleting the null version here would destroy the live
                    # object an aborted overwrite never replaced)
                    if renamed and err is None:
                        disk.delete_version(bucket, obj, fi)
                    disk.delete(TMP_VOLUME, tmp_id, recursive=True)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            raise
        self._sweep_staging(tmp_id, self.disks)
        return self._to_object_info(bucket, obj, fi)

    def _stream_native(
        self,
        paths: list[str],
        reader,
        coder: ErasureCoder,
        fi: FileInfo,
        errs: list[Exception | None],
        write_q: int,
        lock,
        bucket: str,
        obj: str,
    ) -> tuple[str, int]:
        """Drive the C++ streaming PUT plane: md5 + stripe split + GF parity
        + bitrot hashing + shard-file framing + writes happen in one
        GIL-releasing native pass per chunk (native/dataplane.cpp; the
        reference's cmd/erasure-encode.go:76-108 pipeline). Returns
        (md5-hex etag, size); drive failures land in errs by disk position.
        """
        from .. import native
        from ..ops.highwayhash import MINIO_KEY

        ctx = native.DataplanePut(
            coder.d, coder.p, coder.block_size, coder._np.parity_matrix,
            MINIO_KEY, paths,
        )
        size = 0
        try:
            for chunk in reader:
                if not chunk:
                    continue
                if lock is not None and lock.lost:
                    raise QuorumError(
                        f"write lock on {bucket}/{obj} lost mid-stream; aborting"
                    )
                ctx.feed(chunk)
                size += len(chunk)
                if ctx.alive() < write_q:
                    raise QuorumError("write quorum lost mid-stream")
            etag, dead = ctx.finish()
        except BaseException:
            ctx.abort()
            raise
        for i in range(self.n):
            if (dead >> (fi.erasure.distribution[i] - 1)) & 1:
                errs[i] = OSError("native shard write failed")
        if sum(e is None for e in errs) < write_q:
            raise QuorumError("write quorum lost")
        if size:
            # the native plane bypasses the coder, so count its stripe
            # blocks here — the per-family encode series must reflect
            # RS traffic served by C++ too
            family_stats_add(
                bitrot_io.FAMILY_RS, "encode_blocks",
                -(-size // coder.block_size),
            )
        return etag, size

    def _sweep_staging(self, tmp_id: str, disks) -> None:
        """Best-effort removal of a staging dir on drives whose
        rename_data never ran or failed (rename sweeps its own dir):
        staged shard bytes must not outlive the operation that wrote
        them — a partially-failed drive would otherwise keep a full
        shard copy under .minio.sys/tmp until manual cleanup."""
        for disk in disks:
            try:
                disk.delete(TMP_VOLUME, tmp_id, recursive=True)
            except (StorageError, OSError):
                pass  # already gone / drive offline: nothing to sweep

    # -- get ---------------------------------------------------------------

    def get_object_info(self, bucket: str, obj: str, version_id: str = "") -> ObjectInfo:
        fi, _ = self._cached_fileinfo(bucket, obj, version_id)
        if fi.deleted:
            if not version_id:
                raise ObjectNotFound(f"{bucket}/{obj}")
            return self._to_object_info(bucket, obj, fi)
        return self._to_object_info(bucket, obj, fi)

    def open_object(
        self, bucket: str, obj: str, version_id: str = "",
        range_hint=None,
    ) -> tuple[ObjectInfo, "ObjectHandle"]:
        """One quorum metadata read under a namespace read lock; the handle
        serves any number of ranged reads without re-reading metadata.
        Hot objects short-circuit both: a data-cache hit serves an
        immutable verified snapshot from memory — no lock, no metadata
        fan-out, no shard I/O (invalidation through the cache choke point
        happens under the writer's lock BEFORE it releases, so any entry
        found here was the live version when the lookup happened).

        ``range_hint`` is the syntactically-parsed Range header of a
        ranged GET (``("abs", start, end|None)`` / ``("suffix", n)``):
        when every stripe-block segment covering the range is cached
        (range-segment tier, objects far above the whole-object size
        gate), the same short-circuit applies."""
        hit = self.cache.data_get(bucket, obj, version_id)
        if hit is not None:
            fi, data = hit
            from ..cache.core import span_lookup

            span_lookup("object", bucket, obj, True)
            return (
                self._to_object_info(bucket, obj, fi),
                CachedObjectHandle(fi, data),
            )
        if range_hint is not None:
            seg = self.cache.segment_open(bucket, obj, version_id, range_hint)
            if seg is not None:
                fi, start, length, rows = seg
                return (
                    self._to_object_info(bucket, obj, fi),
                    SegmentCachedObjectHandle(
                        self, bucket, obj, version_id, fi, start, length,
                        rows,
                    ),
                )
        with obs.span(
            obs.TYPE_INTERNAL, "erasure.open_object", bucket=bucket, object=obj
        ):
            mtx = self.ns.new(bucket, obj)
            if not _lock_dyn(mtx, write=False):
                raise QuorumError(f"namespace read lock timeout on {bucket}/{obj}")
            try:
                fi, metas = self._cached_fileinfo(bucket, obj, version_id)
                if fi.deleted:
                    raise ObjectNotFound(f"{bucket}/{obj}")
                oi = self._to_object_info(bucket, obj, fi)
                # the read lock stays held while the handle streams (the
                # reference holds GetObject's lock until the reader closes)
                # and is refreshed during long streams; the TTL backstops
                # abandoned handles
                return oi, ObjectHandle(
                    self, bucket, obj, fi, metas, mutex=mtx,
                    requested_vid=version_id,
                )
            except BaseException:
                # everything up to handle construction releases on failure;
                # a raise after lock ownership transferred would
                # double-release
                mtx.runlock()
                raise

    def get_object(
        self,
        bucket: str,
        obj: str,
        version_id: str = "",
        offset: int = 0,
        length: int = -1,
    ) -> tuple[ObjectInfo, Iterator[bytes]]:
        oi, h = self.open_object(bucket, obj, version_id)
        return oi, h.read(offset, length)

    def _shard_sources(
        self, fi: FileInfo, metas: list[FileInfo | None]
    ) -> dict[int, tuple[StorageAPI, FileInfo]]:
        """erasure shard index -> (drive, its FileInfo), for consistent metas."""
        out: dict[int, tuple[StorageAPI, FileInfo]] = {}
        for disk, m in zip(self.disks, metas):
            if m is None or not m.is_valid() or m.deleted:
                continue
            if m.mod_time != fi.mod_time or m.data_dir != fi.data_dir:
                continue
            idx = m.erasure.index - 1
            if 0 <= idx < self.n and idx not in out:
                out[idx] = (disk, m)
        return out

    def _read_range(
        self,
        bucket: str,
        obj: str,
        fi: FileInfo,
        metas: list[FileInfo | None],
        offset: int,
        length: int,
        seg_sink=None,
    ) -> Iterator[bytes]:
        """Span shim over ``_read_range_inner``: the stripe verify +
        reconstruct compute is the GET path's kernel stage, traced as one
        ``tpu`` span covering the generator's whole life (entered at first
        chunk, closed on exhaustion or client disconnect)."""
        with obs.span(
            obs.TYPE_TPU, "stripe.read-verify",
            bucket=bucket, object=obj, offset=offset, bytes=length,
            family=fi.erasure.algorithm or "reedsolomon",
        ):
            yield from self._read_range_inner(
                bucket, obj, fi, metas, offset, length, seg_sink
            )

    def _read_range_inner(
        self,
        bucket: str,
        obj: str,
        fi: FileInfo,
        metas: list[FileInfo | None],
        offset: int,
        length: int,
        seg_sink=None,
    ) -> Iterator[bytes]:
        """Windowed parallel striped read: per-shard reads fan out on a
        thread pool (greedy data-first, parity spill on failure), whole
        windows of same-pattern blocks reconstruct in ONE batched matrix
        apply, and the next window's reads start before the current one is
        decoded (readahead). Mirrors the reference's parallelReader +
        readahead (/root/reference/cmd/erasure-decode.go:32,127-235,
        cmd/erasure-object.go:1429) but trades its per-block goroutine
        choreography for window-batched decode — the TPU-shaped version.
        Spans multiple parts (each part is its own erasure stream).

        ``seg_sink(part#, block#, block_bytes)``: every stripe block the
        read fully materializes (verified + decoded) is offered to the
        range-segment cache — a partial first/last block of a native
        span is offered too and rejected there by length."""
        if length == 0:
            return
        d = fi.erasure.data_blocks
        coder = self.coder_for(fi)  # typed rejection of unknown families
        family = coder.family
        fdig = coder.frame_digests * DIGEST  # digest bytes per block frame group
        sources = self._shard_sources(fi, metas)
        bad: set[int] = set()
        degraded_reported = False

        def report_degraded():
            nonlocal degraded_reported
            if not degraded_reported and self.on_degraded is not None:
                degraded_reported = True
                try:
                    self.on_degraded(bucket, obj)
                # miniovet: ignore[error-taint] -- observer callback
                # isolation: a failing heal-enqueue hook must never fail
                # the GET it was observing
                except Exception:  # noqa: BLE001
                    pass

        if len(sources) < self.n:
            report_degraded()  # some drive lacks this version entirely

        # legacy whole-file shards: raw bytes on disk, one digest in the
        # drive's metadata; read+verify the whole shard once per part.
        # Futures memoize the load so the read pool's concurrent blocks
        # share ONE read+hash instead of racing past a bare dict check.
        from concurrent.futures import Future

        whole_cache: dict[tuple[int, int], Future] = {}
        whole_lock = threading.Lock()

        def read_whole_shard(idx: int, part_num: int, wh, algo) -> bytes:
            k = (idx, part_num)
            with whole_lock:
                fut = whole_cache.get(k)
                owner = fut is None
                if owner:
                    fut = whole_cache[k] = Future()
            if owner:
                try:
                    disk, m = sources[idx]
                    raw = m.inline_data if m.inline_data else disk.read_file(
                        bucket, f"{obj}/{fi.data_dir}/part.{part_num}", 0, -1
                    )
                    fut.set_result(
                        bitrot_io.verify_whole_file(bytes(raw), wh, algo)
                    )
                except Exception as e:  # noqa: BLE001 — typed via the future
                    fut.set_exception(e)
            return fut.result()

        # zero-copy gather: verified shard payloads flow as views of the
        # read buffer (reedsolomon frames; cauchy's interleaved digests
        # make its one assembly copy inherent), and blocks assemble ONCE
        # into a pre-sized buffer served as a memoryview slice
        zc = bufpool.zerocopy_enabled()

        def serve_slice(buf: bytearray, a: int, b: int):
            """Slice an assembled (GC-owned, never recycled) block for
            the response: a view when zero-copy, bytes on the A/B path."""
            return memoryview(buf)[a:b] if zc else bytes(memoryview(buf)[a:b])

        def read_shard_block(part_num: int, idx: int, per: int, f_off: int):
            disk, m = sources[idx]
            wf = _whole_file_hash(m, part_num)
            if wf is not None:
                block_i = f_off // (fdig + coder.shard_size)
                data = read_whole_shard(idx, part_num, *wf)
                blk = data[block_i * coder.shard_size:][:per]
                if len(blk) != per:
                    raise errors.FileCorrupt("short whole-file shard")
                return blk
            if m.inline_data:
                buf = m.inline_data[f_off : f_off + fdig + per]
            else:
                buf = disk.read_file(
                    bucket, f"{obj}/{fi.data_dir}/part.{part_num}", f_off, fdig + per
                )
            return bitrot_io.verify_block(buf, per, family=family, view=zc)

        def read_sub_chunk(
            part_num: int, idx: int, per: int, f_off: int, which: int
        ) -> np.ndarray:
            """Partial-repair read unit: ONE digest||sub-chunk frame of a
            sub-packetized shard block (the other half never moves)."""
            disk, m = sources[idx]
            rel, dlen = bitrot_io.sub_chunk_in_block(per, which)
            off = f_off + rel
            if m.inline_data:
                buf = m.inline_data[off : off + DIGEST + dlen]
            else:
                buf = disk.read_file(
                    bucket, f"{obj}/{fi.data_dir}/part.{part_num}",
                    off, DIGEST + dlen,
                )
            return np.frombuffer(
                bitrot_io.verify_sub_chunk(bytes(buf), dlen), dtype=np.uint8
            )

        # ---- partial-repair plan: sub-packetized family, exactly one ----
        # data shard gone, every helper present — degraded reads fetch
        # the repair fraction instead of d full shards (ops/cauchy.py
        # schedule; any failure inside the plan falls back to the
        # generic full-gather path below, correctness never rides it)
        repair_sched = None
        if family == bitrot_io.FAMILY_CAUCHY and not any(
            c.hash for c in fi.erasure.checksums
        ):
            missing_data = [i for i in range(d) if i not in sources]
            if len(missing_data) == 1:
                sched = coder.repair_schedule(missing_data[0])
                if sched is not None and all(
                    h in sources for h in sched.helpers
                ):
                    repair_sched = sched

        def repair_read_block(
            pnum: int, per: int, f_off: int, lo: int, hi: int
        ):
            """Serve [lo, hi) of one stripe block under the repair plan:
            full frames only for the data shards the range needs, the
            schedule's sub-chunk frames to rebuild the lost one."""
            i_m = repair_sched.missing
            lo_sh, hi_sh = lo // per, (hi - 1) // per
            needed = list(range(lo_sh, min(hi_sh, d - 1) + 1))
            ingress = 0
            full_idx = set(idx for idx in needed if idx != i_m)
            if i_m in needed:
                # every group mate is also a b_helper, so it needs BOTH
                # sub-chunks — one contiguous frame-group read moves the
                # same bytes as two sub-chunk reads with half the
                # round-trips
                full_idx.update(repair_sched.mates)
            full_futs = {
                idx: pool.submit(read_shard_block, pnum, idx, per, f_off)
                for idx in full_idx
            }
            sub_futs = {}
            if i_m in needed:
                for r in repair_sched.b_helpers:
                    if r not in full_futs:
                        sub_futs[(r, 1)] = pool.submit(
                            read_sub_chunk, pnum, r, per, f_off, 1
                        )
                sub_futs[(repair_sched.pb_parity, 1)] = pool.submit(
                    read_sub_chunk, pnum, repair_sched.pb_parity, per, f_off, 1
                )
            try:
                got_full = {
                    idx: np.frombuffer(f.result(), dtype=np.uint8)
                    for idx, f in full_futs.items()
                }
            except BaseException:
                # a failed full read fails the plan (caller falls back to
                # the generic gather): don't leave sub-chunk reads queued
                for f in sub_futs.values():
                    f.cancel()
                raise
            if i_m in needed:
                # same semantics as the generic path's counter: EVERY
                # frame fetched for a block that needs reconstruction —
                # full frames the range needed anyway included — so the
                # per-family comparison stays apples-to-apples
                ingress += len(got_full) * (fdig + per)
                h1, h2 = bitrot_io.sub_lens(per)
                sub2 = {}
                for r in repair_sched.b_helpers:
                    sub2[r] = (
                        got_full[r][h1:] if r in got_full
                        else sub_futs[(r, 1)].result()
                    )
                    ingress += DIGEST + h2 if r not in got_full else 0
                pb = sub_futs[(repair_sched.pb_parity, 1)].result()
                ingress += DIGEST + h2
                # mates were fetched as full frame groups above
                sub1 = {r: got_full[r][:h1] for r in repair_sched.mates}
                got_full[i_m] = coder.repair_data_shard(
                    repair_sched, per, sub2, pb, sub1
                )
                family_stats_add(family, "degraded_ingress_bytes", ingress)
            # single pre-sized assembly (was .tobytes() per shard +
            # b"".join — two full copies of every block)
            out = bytearray(len(needed) * per)
            mv = memoryview(out)
            for j, idx in enumerate(needed):
                mv[j * per : (j + 1) * per] = got_full[idx]
            bufpool.count_copy("gather-join")
            return serve_slice(out, lo - lo_sh * per, hi - lo_sh * per)

        # ---- plan: every stripe block overlapping [offset, offset+length) ----
        plan: list[tuple[int, int, int, int, int]] = []  # (part#, per, f_off, lo, hi)
        pos = 0
        remaining = length
        for part in fi.parts:
            if remaining <= 0:
                break
            if pos + part.size <= offset:
                pos += part.size
                continue
            bpos = pos
            for block_i, (data_len, per) in enumerate(coder.shard_sizes_for(part.size)):
                if remaining <= 0:
                    break
                if bpos + data_len <= offset:
                    bpos += data_len
                    continue
                lo = max(offset - bpos, 0)
                hi = min(lo + remaining, data_len)
                if hi > lo:
                    f_off = bitrot_io.block_offset(
                        coder.shard_size, block_i, family
                    )
                    plan.append((part.number, per, f_off, lo, hi))
                    remaining -= hi - lo
                bpos += data_len
            pos += part.size

        # ---- native fast path: every data shard local, present, on-disk ----
        # One C++ pass per span does pread + bitrot verify + window assembly
        # (native/dataplane.cpp dp_get_span); any failure falls back to the
        # reconstructing windowed path below for the remaining plan.
        # reedsolomon framing only: dp_get_span walks digest||block frames.
        if plan and family == bitrot_io.FAMILY_RS and _native_plane_enabled() and all(
            i in sources and not sources[i][1].inline_data
            and not any(c.hash for c in sources[i][1].erasure.checksums)
            for i in range(d)
        ):
            from .. import native
            from ..ops.highwayhash import MINIO_KEY

            span_budget = int(os.environ.get("MINIO_TPU_READ_SPAN_MB", "16")) << 20
            path_cache: dict[int, list[str] | None] = {}
            k = 0
            ok = True
            while k < len(plan):
                pnum = plan[k][0]
                if pnum not in path_cache:
                    ps: list[str] | None = []
                    for idx in range(d):
                        lp = sources[idx][0].local_path(
                            bucket, f"{obj}/{fi.data_dir}/part.{pnum}"
                        )
                        if lp is None:
                            ps = None
                            break
                        ps.append(lp)
                    path_cache[pnum] = ps
                paths = path_cache[pnum]
                if paths is None:
                    ok = False
                    break
                start = k
                tot = 0
                while k < len(plan) and plan[k][0] == pnum and tot < span_budget:
                    tot += plan[k][4] - plan[k][3]
                    k += 1
                span = plan[start:k]
                arrs = np.asarray(
                    [(s[2], s[1], s[3], s[4]) for s in span], dtype=np.int64
                )
                out = np.empty(tot, dtype=np.uint8)
                rc = native.dp_get_span(
                    paths, d, MINIO_KEY,
                    np.ascontiguousarray(arrs[:, 0]),
                    np.ascontiguousarray(arrs[:, 1]),
                    np.ascontiguousarray(arrs[:, 2]),
                    np.ascontiguousarray(arrs[:, 3]), out,
                )
                if rc != tot:
                    if rc < 0 and rc != native.DP_GET_ENOMEM:
                        # -(block*64 + shard + 1): mark the shard bad
                        bad.add((-rc - 1) % 64)
                        report_degraded()
                    k = start
                    ok = False
                    break
                if seg_sink is not None:
                    # offer whole stripe blocks of this span to the
                    # segment cache (partial head/tail slices are length-
                    # rejected there); bytes are post-verify, same as the
                    # reconstructing path's fills
                    o = 0
                    frame = fdig + coder.shard_size
                    for pnum_s, _per_s, f_off_s, lo_s, hi_s in span:
                        if lo_s == 0:
                            seg_sink(
                                pnum_s, f_off_s // frame,
                                out[o : o + hi_s - lo_s],
                            )
                        o += hi_s - lo_s
                mv = memoryview(out)
                for o in range(0, tot, 1 << 20):
                    yield mv[o : o + (1 << 20)]
            if ok:
                return
            plan = plan[k:]  # resume on the reconstructing path

        pool = _read_pool()
        window = max(1, int(os.environ.get("MINIO_TPU_READ_WINDOW", "8")))
        hedge_budget = self._hedge_budget_s()

        def start_window(win):
            """Submit data-first reads for every block of the window."""
            futs = {}
            for bi, (pnum, per, f_off, _lo, _hi) in enumerate(win):
                for idx in range(d):
                    if idx in sources and idx not in bad:
                        futs[(bi, idx)] = pool.submit(
                            read_shard_block, pnum, idx, per, f_off
                        )
            return futs

        def gather_window(win, futs):
            """Resolve reads until every block has d shards, spilling to
            parity on FAILURE — and, when a straggling drive blows the
            hedge budget, on LATENCY: extra parity reads race the
            straggler and decode around it, whichever reaches d first
            wins (the hedged-read policy; the reference instead pays the
            straggler's full latency before spilling)."""
            got: list[dict[int, bytes]] = [{} for _ in win]
            pending: dict[tuple[int, int], object] = dict(futs)
            rev = {f: k for k, f in pending.items()}
            hedged_idx: set[int] = set()
            hedge_fired = False
            import time as _time

            deadline = (
                _time.monotonic() + hedge_budget
                if hedge_budget is not None else None
            )

            def submit_more(bi: int, racing: bool) -> int:
                """Spill reads for block bi so results (+ inflight unless
                `racing`) can reach d; hedge submissions race stragglers
                instead of counting them."""
                inflight = [k[1] for k in pending if k[0] == bi]
                have = len(got[bi]) + (0 if racing else len(inflight))
                tried = set(got[bi]) | bad | set(inflight)
                cands = [
                    i for i in range(self.n) if i in sources and i not in tried
                ]
                n_sub = 0
                pnum, per, f_off, _lo, _hi = win[bi]
                for idx in cands[: max(d - have, 0)]:
                    f = pool.submit(read_shard_block, pnum, idx, per, f_off)
                    pending[(bi, idx)] = f
                    rev[f] = (bi, idx)
                    if racing:
                        hedged_idx.add(idx)
                    n_sub += 1
                return n_sub

            try:
                while any(len(g) < d for g in got):
                    # keep every deficient block able to reach d (failure
                    # spill)
                    for bi in range(len(win)):
                        if len(got[bi]) >= d:
                            continue
                        inflight = sum(1 for k in pending if k[0] == bi)
                        if len(got[bi]) + inflight < d:
                            if submit_more(bi, False) == 0 and inflight == 0:
                                pnum, _per, f_off, _lo, _hi = win[bi]
                                raise QuorumError(
                                    f"cannot read part {pnum} shard offset "
                                    f"{f_off}: only {len(got[bi])} of {d} "
                                    "shards"
                                )
                    if not pending:
                        continue  # spills just submitted; re-check
                    timeout = None
                    if deadline is not None and not hedge_fired:
                        timeout = max(deadline - _time.monotonic(), 0.0)
                    done, _ = _fut_wait(
                        set(pending.values()), timeout=timeout,
                        return_when=FIRST_COMPLETED,
                    )
                    if not done:
                        # stragglers blew the budget: hedge — race a
                        # parity-decode of the remaining shards against them
                        hedge_fired = True
                        fired = sum(
                            submit_more(bi, True)
                            for bi in range(len(win)) if len(got[bi]) < d
                        )
                        if fired:
                            fault_registry.stats_add("hedge_reads")
                            fault_registry.emit(
                                "hedge.fire", plane="read",
                                bucket=bucket, object=obj,
                                budgetMs=round((hedge_budget or 0.0) * 1e3, 1),
                                reads=fired,
                            )
                        else:
                            deadline = None  # nothing left to hedge with
                        continue
                    for f in done:
                        bi, idx = rev.pop(f)
                        del pending[(bi, idx)]
                        try:
                            got[bi][idx] = f.result()
                        except (errors.FileCorrupt, errors.FileNotFound,
                                errors.DiskNotFound, errors.DiskFull,
                                errors.VolumeNotFound, OSError):
                            # DiskNotFound covers a circuit that opened
                            # BETWEEN the metadata read and this shard read
                            # (latency trip, remote retries exhausted);
                            # VolumeNotFound a bucket that vanished under
                            # a cached-metadata read: the drive is a
                            # failed shard to spill around, not a reason
                            # to fail a GET that still has quorum
                            bad.add(idx)
                            report_degraded()
            finally:
                # success, QuorumError, or anything else: never leave
                # reads (least of all 500ms-straggler hedge bait) hogging
                # the shared pool after this window is decided
                for f in pending.values():
                    f.cancel()
            # window satisfied: settle the hedge bet (win = a hedged
            # shard ended up in some block's decode set)
            if hedged_idx:
                used: set[int] = set()
                for g in got:
                    used.update(sorted(g.keys())[:d])
                fault_registry.stats_add(
                    "hedge_wins" if used & hedged_idx else "hedge_losses"
                )
            return got

        def decode_window(win, got) -> list:
            """Per-block data buffers; same-pattern degraded blocks batch.

            Every block assembles exactly ONCE into a pre-sized buffer
            (shard payload views copy in directly — the old .tobytes()
            per shard + b"".join double copy is gone; the single copy is
            site "gather-join")."""
            out: list = [None] * len(win)
            groups: dict[tuple[tuple[int, ...], int], list[int]] = {}
            for bi in range(len(win)):
                present = tuple(sorted(got[bi].keys())[:d])
                if present == tuple(range(d)):
                    per = win[bi][1]
                    buf = bytearray(d * per)
                    mv = memoryview(buf)
                    for i in range(d):
                        mv[i * per : (i + 1) * per] = got[bi][i]
                    bufpool.count_copy("gather-join")
                    out[bi] = buf
                else:
                    # survivor ingress: every frame fetched for a block
                    # that needs reconstruction (the full-shard cost the
                    # repair plan above avoids)
                    family_stats_add(
                        family, "degraded_ingress_bytes",
                        len(got[bi]) * (fdig + win[bi][1]),
                    )
                    # group by (pattern, shard size): the tail block's per
                    # differs from full blocks and cannot share a stack
                    groups.setdefault((present, win[bi][1]), []).append(bi)
            for (present, per), bis in groups.items():
                missing = tuple(i for i in range(d) if i not in present)
                # build [d, W', per] directly: the contiguous layout the
                # native GF apply consumes, no post-stack transpose
                # copies. The stack is POOLED scratch — recycled the
                # moment reconstruction returns (its outputs are fresh
                # arrays, never views of the stack)
                nb = d * len(bis) * per
                stack_lease = bufpool.get_pool().acquire(nb) if zc else None
                try:
                    if stack_lease is not None:
                        survivors = stack_lease.array[:nb].reshape(
                            d, len(bis), per
                        )
                    else:
                        survivors = np.empty((d, len(bis), per), dtype=np.uint8)
                    for k, i in enumerate(present):
                        for w, bi in enumerate(bis):
                            survivors[k, w] = np.frombuffer(
                                got[bi][i], dtype=np.uint8
                            )
                    rec = coder.reconstruct_data_flat(
                        survivors, present, missing, pool
                    )
                finally:
                    if stack_lease is not None:
                        stack_lease.release()
                mj = {i: j for j, i in enumerate(missing)}
                for w, bi in enumerate(bis):
                    buf = bytearray(d * per)
                    mv = memoryview(buf)
                    for i in range(d):
                        src = rec[mj[i], w] if i in mj else got[bi][i]
                        mv[i * per : (i + 1) * per] = src
                    bufpool.count_copy("gather-join")
                    out[bi] = buf
            return out

        # ---- repair-plan execution: block-serial baseline --------------
        # (MINIO_TPU_REPAIR_WINDOWED=0: one block's sub-chunk reads at a
        # time, any failure abandons the rest of the plan to the generic
        # gather — kept as the A/B lever the windowed executor's
        # wall-clock gate measures against)
        if repair_sched is not None and not _repair_windowed_enabled():
            rest = None
            for k, (pnum, per, f_off, lo, hi) in enumerate(plan):
                try:
                    piece = repair_read_block(pnum, per, f_off, lo, hi)
                except (errors.FileCorrupt, errors.FileNotFound,
                        errors.DiskNotFound, errors.DiskFull,
                        errors.VolumeNotFound, OSError):
                    # a helper failed mid-plan (second fault, bitrot):
                    # the rest of the range takes the generic gather
                    # path, which discovers and spills around failures
                    # itself — partial repair is an optimization, never
                    # a correctness dependency
                    rest = plan[k:]
                    break
                yield piece
            if rest is None:
                return
            plan = rest
            repair_sched = None

        # ---- repair-plan execution: windowed sub-chunk pipeline --------
        # The same shape as the healthy path below: a window's sub-chunk
        # frame reads issue concurrently, the next window's reads start
        # as readahead while the current one decodes, and the hedged-read
        # policy covers the plan — except that for sub-chunk reads the
        # hedged alternative is the generic full-frame gather for that
        # block. A blown budget races it; a mid-read breaker trip
        # (DiskNotFound/DiskFull), bitrot, or second fault degrades to it
        # outright — for that block ONLY. The plan is never abandoned,
        # and every fallback byte re-verifies its frame digest like any
        # generic read, so wrong bytes cannot be served.
        if repair_sched is not None:
            i_m = repair_sched.missing
            SPILL = (errors.FileCorrupt, errors.FileNotFound,
                     errors.DiskNotFound, errors.DiskFull,
                     errors.VolumeNotFound, OSError)

            def repair_frames(per, lo, hi):
                """One block's plan read set: (full-frame shard indices,
                sub-chunk rows, data rows the range needs)."""
                lo_sh, hi_sh = lo // per, (hi - 1) // per
                needed = list(range(lo_sh, min(hi_sh, d - 1) + 1))
                full_idx = set(i for i in needed if i != i_m)
                subs: list[int] = []
                if i_m in needed:
                    # mates need BOTH sub-chunks: one contiguous frame-
                    # group read each (same bytes, half the round-trips)
                    full_idx.update(repair_sched.mates)
                    subs = [r for r in repair_sched.b_helpers
                            if r not in full_idx]
                    subs.append(repair_sched.pb_parity)
                return full_idx, subs, needed

            def start_repair_window(win):
                """Submit every block's plan reads for the window."""
                futs = {}
                for bi, (pnum, per, f_off, lo, hi) in enumerate(win):
                    full_idx, subs, _needed = repair_frames(per, lo, hi)
                    for idx in full_idx:
                        futs[(bi, "full", idx)] = pool.submit(
                            read_shard_block, pnum, idx, per, f_off
                        )
                    for r in subs:
                        futs[(bi, "sub", r)] = pool.submit(
                            read_sub_chunk, pnum, r, per, f_off, 1
                        )
                return futs

            def assemble_repair(entry, full, subs):
                """Plan-complete block -> its [lo, hi) bytes (the compute
                half of repair_read_block; reads already resolved)."""
                pnum, per, f_off, lo, hi = entry
                _full_idx, _subs, needed = repair_frames(per, lo, hi)
                got = {i: np.frombuffer(v, dtype=np.uint8)
                       for i, v in full.items()}
                if i_m in needed:
                    ingress = len(got) * (fdig + per)
                    h1, h2 = bitrot_io.sub_lens(per)
                    sub2 = {}
                    for r in repair_sched.b_helpers:
                        if r in got:
                            sub2[r] = got[r][h1:]
                        else:
                            sub2[r] = subs[r]
                            ingress += DIGEST + h2
                    pb = subs[repair_sched.pb_parity]
                    ingress += DIGEST + h2
                    sub1 = {r: got[r][:h1] for r in repair_sched.mates}
                    got[i_m] = coder.repair_data_shard(
                        repair_sched, per, sub2, pb, sub1
                    )
                    family_stats_add(family, "degraded_ingress_bytes", ingress)
                # single pre-sized assembly (was .tobytes() + b"".join)
                out = bytearray(len(needed) * per)
                mv = memoryview(out)
                for j, i in enumerate(needed):
                    mv[j * per : (j + 1) * per] = got[i]
                bufpool.count_copy("gather-join")
                lo_sh = lo // per
                return serve_slice(out, lo - lo_sh * per, hi - lo_sh * per)

            def gather_repair_window(win, futs):
                """Resolve a window of plan blocks. Each block is its own
                race: the sub-chunk read set vs (once hedged or failed)
                the generic d-shard full gather — whichever completes
                first serves the block. Returns (pieces, full, subs):
                pieces[bi] is fallback-decoded bytes, or None meaning the
                plan reads landed and assembly is deferred (it runs under
                the next window's readahead)."""
                nwin = len(win)
                full = [dict() for _ in range(nwin)]    # bi -> idx: bytes
                subs = [dict() for _ in range(nwin)]    # bi -> row: array
                fb_got = [dict() for _ in range(nwin)]  # fallback frames
                fb_mode = [False] * nwin
                fb_hedge = [False] * nwin
                plan_done = [False] * nwin
                pieces: list[bytes | None] = [None] * nwin
                pending: dict[tuple, object] = dict(futs)
                rev = {f: k for k, f in pending.items()}
                plan_keys: list[set] = [set() for _ in range(nwin)]
                for k in futs:
                    plan_keys[k[0]].add(k)
                hedge_fired = False
                import time as _time

                deadline = (
                    _time.monotonic() + hedge_budget
                    if hedge_budget is not None else None
                )

                def unserved(bi):
                    return pieces[bi] is None and not plan_done[bi]

                def drop_plan_reads(bi):
                    for k in list(plan_keys[bi]):
                        f = pending.pop(k, None)
                        if f is not None:
                            rev.pop(f, None)
                            f.cancel()
                    plan_keys[bi].clear()

                def drop_fb_reads(bi):
                    for k in [k for k in pending
                              if k[0] == bi and k[1] == "fb"]:
                        f = pending.pop(k)
                        rev.pop(f, None)
                        f.cancel()

                def fb_submit(bi) -> int:
                    """Keep fallback block bi able to reach d shards."""
                    pnum, per, f_off, _lo, _hi = win[bi]
                    inflight = [k[2] for k in pending
                                if k[0] == bi and k[1] == "fb"]
                    have = len(fb_got[bi]) + len(inflight)
                    tried = set(fb_got[bi]) | bad | set(inflight)
                    cands = [i for i in range(self.n)
                             if i in sources and i not in tried]
                    n_sub = 0
                    for idx in cands[: max(d - have, 0)]:
                        f = pool.submit(read_shard_block, pnum, idx, per, f_off)
                        pending[(bi, "fb", idx)] = f
                        rev[f] = (bi, "fb", idx)
                        n_sub += 1
                    return n_sub

                def enter_fallback(bi, racing) -> int:
                    """Degrade block bi to the generic gather. ``racing``
                    (hedge) leaves the plan reads inflight to race; a
                    failed plan read drops them instead."""
                    if fb_mode[bi]:
                        return 0
                    fb_mode[bi] = True
                    fb_hedge[bi] = racing
                    if not racing:
                        drop_plan_reads(bi)
                    return fb_submit(bi)

                def finish_plan(bi):
                    """All plan reads landed: settle the race; assembly
                    is deferred to the caller (under readahead)."""
                    plan_done[bi] = True
                    if fb_mode[bi]:
                        if fb_hedge[bi]:
                            fault_registry.stats_add("repair_hedge_losses")
                        drop_fb_reads(bi)

                def finish_fallback(bi):
                    if not unserved(bi) or len(fb_got[bi]) < d:
                        return
                    block = decode_window([win[bi]], [fb_got[bi]])[0]
                    _pnum, _per, _f_off, lo, hi = win[bi]
                    pieces[bi] = serve_slice(block, lo, hi)
                    fault_registry.stats_add("repair_fallback_blocks")
                    if fb_hedge[bi]:
                        fault_registry.stats_add("repair_hedge_wins")
                    drop_plan_reads(bi)

                try:
                    while any(unserved(bi) for bi in range(nwin)):
                        # fallback blocks must stay able to reach d
                        for bi in range(nwin):
                            if not (unserved(bi) and fb_mode[bi]):
                                continue
                            inflight = sum(
                                1 for k in pending
                                if k[0] == bi and k[1] == "fb"
                            )
                            if len(fb_got[bi]) + inflight < d:
                                if (fb_submit(bi) == 0 and inflight == 0
                                        and not plan_keys[bi]):
                                    pnum, _per, f_off, _lo, _hi = win[bi]
                                    raise QuorumError(
                                        f"cannot read part {pnum} shard "
                                        f"offset {f_off}: only "
                                        f"{len(fb_got[bi])} of {d} shards"
                                    )
                        if not pending:
                            continue  # spills just submitted; re-check
                        timeout = None
                        if deadline is not None and not hedge_fired:
                            timeout = max(deadline - _time.monotonic(), 0.0)
                        # plan-only mode needs every read anyway: one
                        # ALL_COMPLETED wait registers each future once.
                        # Once any block races its fallback, settle per
                        # completion (FIRST_COMPLETED) — whichever side
                        # lands first serves without waiting on the loser.
                        racing = hedge_fired or any(fb_mode)
                        done, _ = _fut_wait(
                            set(pending.values()), timeout=timeout,
                            return_when=(
                                FIRST_COMPLETED if racing else ALL_COMPLETED
                            ),
                        )
                        if not done:
                            # plan reads blew the hedge budget: race the
                            # generic full gather for every unserved block
                            hedge_fired = True
                            fired = sum(
                                enter_fallback(bi, True)
                                for bi in range(nwin) if unserved(bi)
                            )
                            if fired:
                                fault_registry.stats_add("repair_hedge_reads")
                                fault_registry.emit(
                                    "hedge.fire", plane="repair",
                                    bucket=bucket, object=obj,
                                    budgetMs=round(
                                        (hedge_budget or 0.0) * 1e3, 1
                                    ),
                                    reads=fired,
                                )
                            else:
                                deadline = None  # nothing left to hedge
                            continue
                        for f in done:
                            key = rev.pop(f, None)
                            if key is None:
                                continue  # read dropped after its race
                            pending.pop(key, None)
                            bi, kind = key[0], key[1]
                            if kind == "fb":
                                try:
                                    fb_got[bi][key[2]] = f.result()
                                except SPILL:
                                    bad.add(key[2])
                                    report_degraded()
                                else:
                                    finish_fallback(bi)
                                continue
                            plan_keys[bi].discard(key)
                            try:
                                if kind == "full":
                                    full[bi][key[2]] = f.result()
                                else:
                                    subs[bi][key[2]] = f.result()
                            except SPILL:
                                # mid-plan breaker trip / bitrot / second
                                # fault: THIS block degrades to the
                                # generic gather; sibling blocks keep
                                # their plan reads
                                if not unserved(bi):
                                    continue
                                if fb_mode[bi]:
                                    # already racing: the plan just lost
                                    # its own race; the gather carries on
                                    drop_plan_reads(bi)
                                else:
                                    enter_fallback(bi, False)
                            else:
                                if unserved(bi) and not plan_keys[bi]:
                                    finish_plan(bi)
                finally:
                    for f in pending.values():
                        f.cancel()
                return pieces, full, subs

            r_windows = [
                plan[i : i + window] for i in range(0, len(plan), window)
            ]
            r_futs = start_repair_window(r_windows[0]) if r_windows else {}
            try:
                for wi, win in enumerate(r_windows):
                    pieces, r_full, r_subs = gather_repair_window(win, r_futs)
                    r_futs = {}
                    if wi + 1 < len(r_windows):
                        r_futs = start_repair_window(r_windows[wi + 1])
                    for bi in range(len(win)):
                        if pieces[bi] is None:
                            # plan-complete blocks decode here, under the
                            # next window's readahead
                            pieces[bi] = assemble_repair(
                                win[bi], r_full[bi], r_subs[bi]
                            )
                        yield pieces[bi]
            finally:
                for f in r_futs.values():
                    f.cancel()
            return

        # ---- pipelined execution: window k+1 reads under window k decode ----
        windows = [plan[i : i + window] for i in range(0, len(plan), window)]
        futs = start_window(windows[0]) if windows else {}
        try:
            for wi, win in enumerate(windows):
                got = gather_window(win, futs)
                futs = {}
                if wi + 1 < len(windows):
                    futs = start_window(windows[wi + 1])  # readahead
                blocks = decode_window(win, got)
                for (pnum, per, f_off, lo, hi), block in zip(win, blocks):
                    if seg_sink is not None:
                        # the decode always materializes the FULL stripe
                        # block (ranged reads only slice at yield time),
                        # so even a partial-range request fills whole
                        # verified segments (the cache copies on admit —
                        # site "cache-fill" — so serving views is safe)
                        seg_sink(
                            pnum, f_off // (fdig + coder.shard_size),
                            block,
                        )
                    yield serve_slice(block, lo, hi)
        finally:
            # abandoned iterator (client hung up) or error: don't let
            # readahead reads+verifies hog the shared pool
            for f in futs.values():
                f.cancel()

    # -- delete ------------------------------------------------------------

    def delete_object(
        self,
        bucket: str,
        obj: str,
        version_id: str = "",
        versioned: bool = False,
    ) -> ObjectInfo:
        """Versioned delete semantics
        (/root/reference/cmd/erasure-object.go DeleteObject):
        - versioned bucket + no version id -> write a delete marker
        - version id given -> remove exactly that version
        - unversioned -> remove the null version entirely
        """
        with obs.span(
            obs.TYPE_INTERNAL, "erasure.delete_object", bucket=bucket, object=obj
        ):
            mtx = self.ns.new(bucket, obj)
            if not _lock_dyn(mtx, write=True):
                raise QuorumError(f"namespace write lock timeout on {bucket}/{obj}")
            try:
                oi = self._delete_object_locked(bucket, obj, version_id, versioned)
            finally:
                mtx.unlock()
            # invalidate + broadcast outside the lock, before returning
            self.cache.invalidate_object(bucket, obj)
            return oi

    def _delete_object_locked(
        self, bucket: str, obj: str, version_id: str, versioned: bool
    ) -> ObjectInfo:
        write_q = self.n // 2 + 1
        if versioned and not version_id:
            fi = FileInfo(volume=bucket, name=obj)
            fi.version_id = str(uuid.uuid4())
            fi.deleted = True
            fi.mod_time = now_ns()
            fi.erasure.distribution = hash_order(f"{bucket}/{obj}", self.n)
            res = self._parallel(lambda d: d.write_metadata(bucket, obj, fi))
            reduce_quorum_errs([e for _, e in res], write_q)
            oi = self._to_object_info(bucket, obj, fi)
            oi.delete_marker = True
            return oi

        fi = FileInfo(volume=bucket, name=obj, version_id=version_id)
        res = self._parallel(lambda d: d.delete_version(bucket, obj, fi))
        errs = [e for _, e in res]
        reduce_quorum_errs(
            errs, write_q, ignored=(errors.FileNotFound, errors.FileVersionNotFound)
        )
        if all(e is not None for e in errs):
            reduce_quorum_errs(errs, write_q)
        oi = ObjectInfo(bucket=bucket, name=obj, version_id=version_id)
        return oi

    # -- object tags -------------------------------------------------------

    TAGS_META_KEY = TAGS_META_KEY  # module constant, kept as class attr for callers

    def update_object_metadata(
        self, bucket: str, obj: str, version_id: str, mutate
    ) -> None:
        """Quorum read-modify-write of a version's metadata under the
        namespace write lock. `mutate(metadata_dict)` edits in place.
        Serves tagging, retention, and legal holds."""
        mtx = self.ns.new(bucket, obj)
        if not _lock_dyn(mtx, write=True):
            raise QuorumError(f"lock timeout updating {bucket}/{obj}")
        try:
            # read_data=True: the rewrite below persists the FileInfo as-is,
            # so inline payloads must ride along (the metadata-only read
            # masks them to an empty marker, which would wipe the object)
            fi, metas, _, write_q = self._quorum_fileinfo(
                bucket, obj, version_id, read_data=True
            )
            if fi.deleted:
                raise ObjectNotFound(f"{bucket}/{obj}")

            errs = []
            for disk, m in zip(self.disks, metas):
                try:
                    if m is None:
                        raise errors.FileNotFound(obj)
                    mutate(m.metadata)
                    disk.update_metadata(bucket, obj, m)
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            reduce_quorum_errs(errs, write_q)
        finally:
            mtx.unlock()
        self.cache.invalidate_object(bucket, obj)

    def transition_object(
        self, bucket: str, obj: str, tier: str, remote_key: str,
        version_id: str = "", restub: bool = False,
    ) -> None:
        """Replace a version's local data with a metadata stub pointing at
        warm-tier storage (reference cmd/bucket-lifecycle.go transition
        workers). Size/etag/mod_time are preserved; parts are dropped so
        the scanner/heal planes treat the stub as data-free. restub=True
        re-stubs an already-transitioned object whose restored copy
        expired (data is already in the tier)."""
        mtx = self.ns.new(bucket, obj)
        if not _lock_dyn(mtx, write=True):
            raise QuorumError(f"lock timeout transitioning {bucket}/{obj}")
        try:
            from ..ilm.tier import RESTORE_EXPIRY_META, TRANSITION_KEY_META, TRANSITION_TIER_META

            fi, metas, _, write_q = self._quorum_fileinfo(
                bucket, obj, version_id, read_data=True
            )
            if fi.deleted:
                raise ObjectNotFound(f"{bucket}/{obj}")
            already = bool(fi.metadata.get(TRANSITION_TIER_META))
            if already and not restub:
                # miniovet: ignore[coherence-path] -- nothing written,
                # nothing stale: the object is already transitioned
                return
            if restub and not already:
                # miniovet: ignore[coherence-path] -- nothing written,
                # nothing stale: no restored copy to re-stub
                return
            old_data_dir = fi.data_dir
            nfi = FileInfo.from_dict(fi.to_dict())
            nfi.parts = []
            nfi.data_dir = None
            nfi.inline_data = None
            if restub:
                nfi.metadata.pop(RESTORE_EXPIRY_META, None)
            else:
                nfi.metadata[TRANSITION_TIER_META] = tier
                nfi.metadata[TRANSITION_KEY_META] = remote_key
            errs = []
            for i, disk in enumerate(self.disks):
                try:
                    dfi = FileInfo.from_dict(nfi.to_dict())
                    dfi.volume, dfi.name = bucket, obj
                    dfi.erasure.index = fi.erasure.distribution[i]
                    disk.write_metadata(bucket, obj, dfi)
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            reduce_quorum_errs(errs, write_q)
            if old_data_dir:
                for disk in self.disks:
                    try:
                        disk.delete(bucket, f"{obj}/{old_data_dir}", recursive=True)
                    except (StorageError, OSError):
                        pass  # already absent / drive offline
        finally:
            mtx.unlock()
        self.cache.invalidate_object(bucket, obj)

    def restore_object(
        self, bucket: str, obj: str, data: bytes, days: int, version_id: str = ""
    ) -> None:
        """Bring a transitioned version's data back locally for `days`
        (reference RestoreObject, cmd/bucket-lifecycle.go restoreObject).
        The object STAYS transitioned; the scanner re-stubs it after the
        restore window."""
        import time as _time

        mtx = self.ns.new(bucket, obj)
        if not _lock_dyn(mtx, write=True):
            raise QuorumError(f"lock timeout restoring {bucket}/{obj}")
        try:
            from ..ilm.tier import RESTORE_EXPIRY_META, TRANSITION_TIER_META

            fi, metas, _, write_q = self._quorum_fileinfo(
                bucket, obj, version_id, read_data=True
            )
            if fi.deleted or not fi.metadata.get(TRANSITION_TIER_META):
                raise ObjectNotFound(f"{bucket}/{obj} is not transitioned")
            # restored shards keep the object's STORED family: its
            # xl.meta algorithm field survives the restore round-trip
            encoded = self.coder_for(fi).encode_part(data)
            nfi = FileInfo.from_dict(fi.to_dict())
            nfi.data_dir = str(uuid.uuid4())
            nfi.parts = [
                ObjectPartInfo(1, len(data), len(data), fi.mod_time,
                               fi.metadata.get("etag", ""))
            ]
            nfi.metadata[RESTORE_EXPIRY_META] = str(
                _time.time() + days * 86400
            )
            tmp_id = str(uuid.uuid4())
            errs = []
            for i, disk in enumerate(self.disks):
                try:
                    shard_idx = fi.erasure.distribution[i] - 1
                    dfi = FileInfo.from_dict(nfi.to_dict())
                    dfi.volume, dfi.name = bucket, obj
                    dfi.erasure.index = shard_idx + 1
                    stage = f"{tmp_id}/{nfi.data_dir}/part.1"
                    disk.create_file(TMP_VOLUME, stage, encoded.shard_files[shard_idx])
                    disk.rename_data(TMP_VOLUME, tmp_id, dfi, bucket, obj)
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            # drives that staged but never finished rename_data keep a
            # full restored shard under .minio.sys/tmp — sweep them
            # whether or not quorum held (on failure every drive may)
            self._sweep_staging(
                tmp_id,
                (d for d, e in zip(self.disks, errs) if e is not None),
            )
            reduce_quorum_errs(errs, write_q)
        finally:
            mtx.unlock()
        self.cache.invalidate_object(bucket, obj)

    def set_object_tags(
        self, bucket: str, obj: str, tags: dict[str, str], version_id: str = ""
    ) -> None:
        """Store object tags in version metadata (reference PutObjectTags,
        cmd/erasure-object.go)."""
        import urllib.parse as _up

        encoded = _up.urlencode(tags)

        def mutate(md: dict) -> None:
            if encoded:
                md[self.TAGS_META_KEY] = encoded
            else:
                md.pop(self.TAGS_META_KEY, None)

        self.update_object_metadata(bucket, obj, version_id, mutate)

    def get_object_tags(
        self, bucket: str, obj: str, version_id: str = ""
    ) -> dict[str, str]:
        import urllib.parse as _up

        fi, _ = self._cached_fileinfo(bucket, obj, version_id)
        raw = fi.metadata.get(self.TAGS_META_KEY, "")
        # empty tag VALUES are legal ("env=") and must round-trip
        return dict(_up.parse_qsl(raw, keep_blank_values=True))

    # -- versions ----------------------------------------------------------

    def list_object_versions(self, bucket: str, obj: str) -> list[ObjectInfo]:
        res = self._parallel(lambda d: d.read_versions(bucket, obj))
        for vers, err in res:
            if err is None:
                return [self._to_object_info(bucket, obj, fi) for fi in vers]
        return []

    # -- heal --------------------------------------------------------------

    def heal_object(self, bucket: str, obj: str, version_id: str = "") -> dict:
        """Rebuild missing/corrupt shards onto stale drives.

        Mirrors healObject (/root/reference/cmd/erasure-healing.go:295):
        quorum-pick the authoritative version, classify each drive as ok or
        stale (missing version, bad metadata, or failing bitrot verify),
        reconstruct stale shards from healthy ones, rename into place.
        Holds the namespace write lock: healing must not interleave with a
        concurrent overwrite of the same object.
        """
        with obs.span(
            obs.TYPE_HEAL, "erasure.heal_object", bucket=bucket, object=obj
        ) as hsp:
            mtx = self.ns.new(bucket, obj)
            if not _lock_dyn(mtx, write=True):
                raise QuorumError(f"namespace lock timeout healing {bucket}/{obj}")
            try:
                res = self._heal_object_locked(bucket, obj, version_id, lock=mtx)
                hsp.set(
                    healed=len(res.get("healed", [])),
                    family=res.get("family", ""),
                    ingressBytes=res.get("ingressBytes", 0),
                )
            finally:
                mtx.unlock()
            if res.get("healed"):
                # healed shards change per-drive metadata/frames: cached
                # metas and bytes re-resolve (fault-injected bitrot/
                # torn-write repairs flow through here too)
                self.cache.invalidate_object(bucket, obj)
            # miniovet: ignore[coherence-path] -- the invalidation above
            # is conditional on purpose: a heal that repaired nothing
            # changed nothing, so there is nothing stale to drop
            return res

    def _heal_object_locked(
        self, bucket: str, obj: str, version_id: str, lock=None
    ) -> dict:
        fi, metas, read_q, write_q = self._quorum_fileinfo(
            bucket, obj, version_id, read_data=True
        )
        if lock is not None and fi.size > (8 << 20):
            # healing big objects can outlive the TTL; a healer that lost
            # its lock must not rename stale shards over a concurrent write
            lock.start_refresher(write=True)
        if fi.deleted:
            # replicate the delete marker onto drives that miss it
            healed = []
            for disk, m in zip(self.disks, metas):
                if m is None or m.version_id != fi.version_id:
                    try:
                        disk.write_metadata(bucket, obj, fi)
                        healed.append(disk.endpoint)
                    except (StorageError, OSError):
                        pass  # heal is per-drive best-effort
            return {"healed": healed, "type": "delete-marker"}

        d, p = fi.erasure.data_blocks, fi.erasure.parity_blocks
        coder = self.coder_for(fi)  # stored family; unknown -> typed error
        family = coder.family
        fdig = coder.frame_digests * DIGEST
        sources = self._shard_sources(fi, metas)

        # verify the shards we think are good; drop any that fail bitrot
        good: dict[int, tuple[StorageAPI, FileInfo]] = {}
        for idx, (disk, m) in sources.items():
            try:
                if m.inline_data:
                    self._verify_inline(m, coder)
                else:
                    disk.verify_file(bucket, obj, m)
                good[idx] = (disk, m)
            except (StorageError, OSError, ValueError):
                pass  # corrupt/unreadable shard: heal rebuilds it below
        if len(good) < d:
            raise QuorumError(f"not enough healthy shards to heal: {len(good)}/{d}")

        stale: list[tuple[int, StorageAPI]] = []
        by_disk = {id(disk): idx for idx, (disk, _) in good.items()}
        for i, disk in enumerate(self.disks):
            if id(disk) not in by_disk:
                shard_idx = fi.erasure.distribution[i] - 1
                stale.append((shard_idx, disk))
        if not stale:
            return {"healed": [], "type": "object"}

        # rebuild the full shard files for stale drives, part by part —
        # FULL stripe blocks batch onto the device (one reconstruct matmul
        # + one hash dispatch for many blocks, the HealObject north-star);
        # tails and small objects take the native CPU path
        per_part_rebuilt: dict[int, dict[int, bytearray]] = {}
        survivors_idx = sorted(good.keys())[:d]
        missing_idx = tuple(sorted(idx for idx, _ in stale))

        heal_whole_cache: dict[tuple[int, int], bytes] = {}
        heal_whole_mu = threading.Lock()
        # survivor bytes moved into this heal (the repair-bandwidth
        # number: metrics minio_heal_ingress_bytes_total, heal span).
        # The windowed repair executor fans reads onto the shared pool,
        # so the accumulator takes a lock.
        ingress = 0
        ingress_mu = threading.Lock()

        def ingress_add(n: int) -> None:
            nonlocal ingress
            with ingress_mu:
                ingress += n

        def read_block(part, idx, f_off, per):
            disk, m = good[idx]
            wf = _whole_file_hash(m, part.number)
            if wf is not None:  # legacy whole-file survivor
                k = (idx, part.number)
                # coarse lock: legacy survivors are rare and the whole-
                # file read+verify must happen once, not once per racing
                # windowed block
                with heal_whole_mu:
                    if k not in heal_whole_cache:
                        raw = m.inline_data if m.inline_data else disk.read_file(
                            bucket, f"{obj}/{fi.data_dir}/part.{part.number}",
                            0, -1,
                        )
                        ingress_add(len(raw))
                        heal_whole_cache[k] = bitrot_io.verify_whole_file(
                            bytes(raw), *wf
                        )
                block_i = f_off // (fdig + coder.shard_size)
                blk = heal_whole_cache[k][block_i * coder.shard_size:][:per]
                if len(blk) != per:
                    raise errors.FileCorrupt("short whole-file shard")
                return blk
            if m.inline_data:
                buf = m.inline_data[f_off : f_off + fdig + per]
            else:
                buf = disk.read_file(
                    bucket, f"{obj}/{fi.data_dir}/part.{part.number}",
                    f_off, fdig + per,
                )
            ingress_add(len(buf))
            return bitrot_io.verify_block(buf, per, family=family)

        def read_sub(part, idx, f_off, per, which):
            """Sub-chunk frame read from a survivor (partial repair)."""
            disk, m = good[idx]
            rel, dlen = bitrot_io.sub_chunk_in_block(per, which)
            off = f_off + rel
            if m.inline_data:
                buf = m.inline_data[off : off + DIGEST + dlen]
            else:
                buf = disk.read_file(
                    bucket, f"{obj}/{fi.data_dir}/part.{part.number}",
                    off, DIGEST + dlen,
                )
            ingress_add(len(buf))
            return np.frombuffer(
                bitrot_io.verify_sub_chunk(bytes(buf), dlen), dtype=np.uint8
            )

        # healed shards keep the OBJECT's format: streaming objects get
        # family-framed digest||block records, legacy whole-file objects
        # raw bytes plus a fresh metadata digest (healed in kind)
        whole = any(c.hash for c in fi.erasure.checksums)

        # partial-repair plan: ONE stale data shard of a sub-packetized
        # family rebuilds from the schedule's sub-chunk reads — the
        # direct lever on survivor bytes moved (ROADMAP item 2). Any
        # read failure falls back to the generic full-read rebuild.
        repair_sched = None
        if (
            family == bitrot_io.FAMILY_CAUCHY
            and not whole
            and len(stale) == 1
            and stale[0][0] < d
        ):
            sched = coder.repair_schedule(stale[0][0])
            if sched is not None and all(h in good for h in sched.helpers):
                repair_sched = sched

        def repair_part_windowed(part, geometry) -> bytearray:
            """Windowed + hedged partial repair of one part's lost shard
            (the heal twin of the degraded-GET plan executor): a window
            of blocks' sub-chunk reads issues concurrently on the shard-
            read pool, the next window starts as readahead while the
            current one frames (hash + emit), and a straggling or failed
            helper degrades THAT block to a generic survivor rebuild —
            racing it as the hedge when the EWMA budget blows. Raises
            only when a block can neither repair nor rebuild from the
            verified survivor set (the caller then falls back to the
            generic whole-part path). Returns the lost shard's framed
            bytes for the whole part, in block order."""
            sched = repair_sched
            s_idx = sched.missing
            pool = _read_pool()
            window = max(1, int(os.environ.get("MINIO_TPU_READ_WINDOW", "8")))
            hedge_budget = self._hedge_budget_s()
            SPILL = (StorageError, OSError)

            def start_win(blocks):
                """Submit one window's plan reads: mates as full frame
                groups (they need both sub-chunks), the remaining
                b_helpers + piggyback parity as sub-chunk-2 frames."""
                futs = {}
                for bi, (block_i, per) in enumerate(blocks):
                    f_off = bitrot_io.block_offset(
                        coder.shard_size, block_i, family
                    )
                    for r in sched.mates:
                        futs[(bi, "full", r)] = pool.submit(
                            read_block, part, r, f_off, per
                        )
                    for r in sched.b_helpers:
                        if r not in sched.mates:
                            futs[(bi, "sub", r)] = pool.submit(
                                read_sub, part, r, f_off, per, 1
                            )
                    futs[(bi, "sub", sched.pb_parity)] = pool.submit(
                        read_sub, part, sched.pb_parity, f_off, per, 1
                    )
                return futs

            def assemble(blocks, bi, fullm, subm) -> np.ndarray:
                _block_i, per = blocks[bi]
                h1m, _h2m = bitrot_io.sub_lens(per)
                mate_full = {
                    r: np.frombuffer(fullm[bi][r], dtype=np.uint8)
                    for r in sched.mates
                }
                sub2 = {
                    r: (mate_full[r][h1m:] if r in mate_full else subm[bi][r])
                    for r in sched.b_helpers
                }
                pb = subm[bi][sched.pb_parity]
                sub1 = {r: v[:h1m] for r, v in mate_full.items()}
                return coder.repair_data_shard(sched, per, sub2, pb, sub1)

            def gather_win(blocks, futs):
                """Resolve one window; every block races its plan reads
                against (once hedged or failed) a generic survivor
                rebuild. Returns the rebuilt shard per block."""
                nb = len(blocks)
                fullm = [dict() for _ in range(nb)]
                subm = [dict() for _ in range(nb)]
                fb_got = [dict() for _ in range(nb)]
                fb_bad: set[int] = set()  # shards whose fb read failed
                fb_mode = [False] * nb
                fb_hedge = [False] * nb
                shards: list[np.ndarray | None] = [None] * nb
                plan_keys: list[set] = [set() for _ in range(nb)]
                pending: dict[tuple, object] = dict(futs)
                rev = {f: k for k, f in pending.items()}
                for k in futs:
                    plan_keys[k[0]].add(k)
                last_err: BaseException | None = None
                hedge_fired = False
                import time as _time

                deadline = (
                    _time.monotonic() + hedge_budget
                    if hedge_budget is not None else None
                )

                def drop_plan(bi):
                    for k in list(plan_keys[bi]):
                        f = pending.pop(k, None)
                        if f is not None:
                            rev.pop(f, None)
                            f.cancel()
                    plan_keys[bi].clear()

                def drop_fb(bi):
                    for k in [k for k in pending
                              if k[0] == bi and k[1] == "fb"]:
                        f = pending.pop(k)
                        rev.pop(f, None)
                        f.cancel()

                def fb_submit(bi) -> int:
                    block_i, per = blocks[bi]
                    f_off = bitrot_io.block_offset(
                        coder.shard_size, block_i, family
                    )
                    inflight = [k[2] for k in pending
                                if k[0] == bi and k[1] == "fb"]
                    have = len(fb_got[bi]) + len(inflight)
                    tried = set(fb_got[bi]) | set(inflight) | fb_bad
                    cands = [i for i in sorted(good) if i not in tried]
                    n_sub = 0
                    for idx in cands[: max(d - have, 0)]:
                        f = pool.submit(read_block, part, idx, f_off, per)
                        pending[(bi, "fb", idx)] = f
                        rev[f] = (bi, "fb", idx)
                        n_sub += 1
                    return n_sub

                def enter_fb(bi, racing) -> int:
                    if fb_mode[bi]:
                        return 0
                    fb_mode[bi] = True
                    fb_hedge[bi] = racing
                    if not racing:
                        drop_plan(bi)
                    return fb_submit(bi)

                def finish_plan(bi):
                    shards[bi] = assemble(blocks, bi, fullm, subm)
                    if fb_mode[bi]:
                        if fb_hedge[bi]:
                            fault_registry.stats_add("repair_hedge_losses")
                        drop_fb(bi)

                def finish_fb(bi):
                    if shards[bi] is not None or len(fb_got[bi]) < d:
                        return
                    got = {
                        i: np.frombuffer(v, dtype=np.uint8)
                        for i, v in fb_got[bi].items()
                    }
                    rec = coder.reconstruct_block(got, blocks[bi][1])
                    shards[bi] = rec[s_idx]
                    fault_registry.stats_add("repair_fallback_blocks")
                    if fb_hedge[bi]:
                        fault_registry.stats_add("repair_hedge_wins")
                    drop_plan(bi)

                try:
                    while any(s is None for s in shards):
                        for bi in range(nb):
                            if shards[bi] is not None or not fb_mode[bi]:
                                continue
                            inflight = sum(
                                1 for k in pending
                                if k[0] == bi and k[1] == "fb"
                            )
                            if len(fb_got[bi]) + inflight < d:
                                if (fb_submit(bi) == 0 and inflight == 0
                                        and not plan_keys[bi]):
                                    # neither path can complete: the
                                    # caller rebuilds this part the
                                    # generic way
                                    raise last_err or errors.FileCorrupt(
                                        "repair fallback lost quorum"
                                    )
                        if not pending:
                            continue
                        timeout = None
                        if deadline is not None and not hedge_fired:
                            timeout = max(deadline - _time.monotonic(), 0.0)
                        # plan-only mode needs every read anyway: one
                        # ALL_COMPLETED wait registers each future once.
                        # Once any block races its fallback, settle per
                        # completion (FIRST_COMPLETED) — whichever side
                        # lands first serves without waiting on the loser.
                        racing = hedge_fired or any(fb_mode)
                        done, _ = _fut_wait(
                            set(pending.values()), timeout=timeout,
                            return_when=(
                                FIRST_COMPLETED if racing else ALL_COMPLETED
                            ),
                        )
                        if not done:
                            hedge_fired = True
                            fired = sum(
                                enter_fb(bi, True)
                                for bi in range(nb) if shards[bi] is None
                            )
                            if fired:
                                fault_registry.stats_add("repair_hedge_reads")
                                fault_registry.emit(
                                    "hedge.fire", plane="repair", op="heal",
                                    bucket=bucket, object=obj,
                                    budgetMs=round(
                                        (hedge_budget or 0.0) * 1e3, 1
                                    ),
                                    reads=fired,
                                )
                            else:
                                deadline = None
                            continue
                        for f in done:
                            key = rev.pop(f, None)
                            if key is None:
                                continue
                            pending.pop(key, None)
                            bi, kind = key[0], key[1]
                            if kind == "fb":
                                try:
                                    fb_got[bi][key[2]] = f.result()
                                except SPILL as e:
                                    # a failed fallback shard must never
                                    # be re-picked (a persistently
                                    # corrupt helper would loop forever)
                                    last_err = e
                                    fb_bad.add(key[2])
                                else:
                                    finish_fb(bi)
                                continue
                            plan_keys[bi].discard(key)
                            try:
                                if kind == "full":
                                    fullm[bi][key[2]] = f.result()
                                else:
                                    subm[bi][key[2]] = f.result()
                            except SPILL as e:
                                last_err = e
                                if shards[bi] is not None:
                                    continue
                                if fb_mode[bi]:
                                    drop_plan(bi)  # plan lost its race
                                else:
                                    enter_fb(bi, False)
                            else:
                                if shards[bi] is None and not plan_keys[bi]:
                                    finish_plan(bi)
                finally:
                    for f in pending.values():
                        f.cancel()
                return shards

            out = bytearray()
            blocks_all = [
                (block_i, per)
                for block_i, (_data_len, per) in enumerate(geometry)
            ]
            wins = [
                blocks_all[i : i + window]
                for i in range(0, len(blocks_all), window)
            ]
            futs = start_win(wins[0]) if wins else {}
            try:
                for wi, blocks in enumerate(wins):
                    shards = gather_win(blocks, futs)
                    futs = {}
                    if wi + 1 < len(wins):
                        futs = start_win(wins[wi + 1])  # readahead
                    for blk in shards:
                        # framing (bitrot hash + emit) runs under the
                        # next window's readahead
                        out += bitrot_io.frame_block(blk.tobytes(), family)
            finally:
                for f in futs.values():
                    f.cancel()
            return out

        for part in fi.parts:
            geometry = coder.shard_sizes_for(part.size)
            rebuilt: dict[int, bytearray] = {idx: bytearray() for idx, _ in stale}
            full_n = sum(1 for _, per in geometry if per == coder.shard_size)
            # device heal wins only when the accelerator link is fast
            # (PCIe-class); over a slow tunnel the native AVX2 path is
            # several times faster — see PERF.md heal measurements
            import os as _os

            use_device = (
                coder._jax is not None
                and family == bitrot_io.FAMILY_RS
                and full_n >= 4
                and not fi.inline_data
                and not whole  # device path emits streaming frames only
                and _os.environ.get("MINIO_TPU_DEVICE_HEAL", "0") == "1"
            )
            batched_done = 0
            if repair_sched is not None:
                s_idx = repair_sched.missing
                try:
                    if _repair_windowed_enabled():
                        # windowed + hedged executor: straggling/failed
                        # helpers degrade per BLOCK to a generic survivor
                        # rebuild inside repair_part_windowed; only a
                        # block that can do neither lands here
                        rebuilt[s_idx] += repair_part_windowed(
                            part, geometry
                        )
                    else:
                        # block-serial baseline
                        # (MINIO_TPU_REPAIR_WINDOWED=0)
                        for block_i, (data_len, per) in enumerate(geometry):
                            f_off = bitrot_io.block_offset(
                                coder.shard_size, block_i, family
                            )
                            # group mates need BOTH sub-chunks (every
                            # mate is a b_helper): one full frame-group
                            # read each — same bytes as two sub-chunk
                            # reads, half the ops
                            h1m, _h2m = bitrot_io.sub_lens(per)
                            mate_full = {
                                r: np.frombuffer(
                                    read_block(part, r, f_off, per),
                                    dtype=np.uint8,
                                )
                                for r in repair_sched.mates
                            }
                            sub2 = {
                                r: (
                                    mate_full[r][h1m:] if r in mate_full
                                    else read_sub(part, r, f_off, per, 1)
                                )
                                for r in repair_sched.b_helpers
                            }
                            pb = read_sub(
                                part, repair_sched.pb_parity, f_off, per, 1
                            )
                            sub1 = {r: v[:h1m] for r, v in mate_full.items()}
                            blk = coder.repair_data_shard(
                                repair_sched, per, sub2, pb, sub1
                            )
                            rebuilt[s_idx] += bitrot_io.frame_block(
                                blk.tobytes(), family
                            )
                    per_part_rebuilt[part.number] = rebuilt
                    continue
                except (StorageError, OSError):
                    # helper failed mid-repair AND the per-block fallback
                    # lost quorum: rebuild THIS part the generic way (and
                    # stop trying the shortcut — the survivor set just
                    # proved unreliable)
                    repair_sched = None
                    rebuilt = {idx: bytearray() for idx, _ in stale}
            if use_device:
                from ..ops.bitrot_jax import reconstruct_and_hash

                max_blocks = max(1, 3072 // max(len(missing_idx), 1))
                for start in range(0, full_n, max_blocks):
                    count = min(max_blocks, full_n - start)
                    surv = np.empty(
                        (count, d, coder.shard_size), dtype=np.uint8
                    )
                    for bi in range(count):
                        f_off = bitrot_io.block_offset(
                            coder.shard_size, start + bi
                        )
                        for si, idx in enumerate(survivors_idx):
                            surv[bi, si] = np.frombuffer(
                                read_block(part, idx, f_off, coder.shard_size),
                                dtype=np.uint8,
                            )
                    # reconstruct + bitrot-hash in one device dispatch:
                    # rebuilt shards are hashed while still resident
                    recon_d, digs_d = reconstruct_and_hash(
                        coder._jax, surv, tuple(survivors_idx), missing_idx
                    )
                    recon = np.asarray(recon_d)
                    digs = np.asarray(digs_d)
                    for bi in range(count):
                        for mi, idx in enumerate(missing_idx):
                            rebuilt[idx] += digs[bi, mi].tobytes()
                            rebuilt[idx] += recon[bi, mi].tobytes()
                batched_done = full_n
            for block_i, (data_len, per) in enumerate(geometry):
                if block_i < batched_done:
                    continue
                f_off = bitrot_io.block_offset(coder.shard_size, block_i, family)
                got: dict[int, np.ndarray] = {}
                for idx in survivors_idx:
                    got[idx] = np.frombuffer(
                        read_block(part, idx, f_off, per), dtype=np.uint8
                    )
                rec = coder.reconstruct_block(got, per)
                for idx, _ in stale:
                    blk = rec[idx].tobytes()
                    if not whole:
                        rebuilt[idx] += bitrot_io.frame_block(blk, family)
                    else:
                        rebuilt[idx] += blk
            per_part_rebuilt[part.number] = rebuilt
        if lock is not None and lock.lost:
            raise QuorumError(f"heal lock on {bucket}/{obj} lost; aborting commit")
        family_stats_add(family, "heal_ingress_bytes", ingress)
        healed = []
        tmp_id = str(uuid.uuid4())
        for shard_idx, disk in stale:
            dfi = FileInfo.from_dict(fi.to_dict())
            dfi.volume, dfi.name = bucket, obj
            dfi.erasure.index = shard_idx + 1
            if whole:
                # this drive's metadata must carry ITS shard's digests, not
                # the survivor's (checksums are per-drive in this format);
                # keep the object's stored algorithm (legacy may be sha256)
                from ..ops.bitrot import algorithm_from_string

                algo_str = next(
                    (c.algorithm for c in fi.erasure.checksums if c.hash),
                    DEFAULT_BITROT_ALGO.string,
                )
                dfi.erasure.checksums = [
                    ChecksumInfo(p.number, algo_str,
                                 bitrot_io.whole_file_digest(
                                     bytes(per_part_rebuilt[p.number][shard_idx]),
                                     algorithm_from_string(algo_str)))
                    for p in fi.parts
                ]
            try:
                if fi.inline_data is not None or not fi.data_dir:
                    dfi.inline_data = bytes(per_part_rebuilt[fi.parts[0].number][shard_idx])
                    disk.write_metadata(bucket, obj, dfi)
                else:
                    for part in fi.parts:
                        stage = f"{tmp_id}/{fi.data_dir}/part.{part.number}"
                        disk.create_file(
                            TMP_VOLUME, stage, bytes(per_part_rebuilt[part.number][shard_idx])
                        )
                    disk.rename_data(TMP_VOLUME, tmp_id, dfi, bucket, obj)
                healed.append(disk.endpoint)
            except (StorageError, OSError):
                # heal is per-drive best-effort, but staged parts on the
                # failed drive must not outlive the attempt
                self._sweep_staging(tmp_id, [disk])
        return {
            "healed": healed, "type": "object", "family": family,
            "ingressBytes": ingress,
            "partialRepair": repair_sched is not None,
        }

    def _verify_inline(self, m: FileInfo, coder: ErasureCoder) -> None:
        data = m.inline_data or b""
        fdig = coder.frame_digests * DIGEST
        off = 0
        for _, per in coder.shard_sizes_for(m.size):
            bitrot_io.verify_block(
                data[off : off + fdig + per], per, family=coder.family
            )
            off += fdig + per

    # -- misc --------------------------------------------------------------

    def walk_objects(self, bucket: str, prefix: str = ""):
        from . import listing

        yield from listing._merged_keys(self, bucket, prefix)

    def _to_object_info(self, bucket: str, obj: str, fi: FileInfo) -> ObjectInfo:
        return ObjectInfo(
            bucket=bucket,
            name=obj,
            version_id=fi.version_id,
            is_latest=fi.is_latest,
            delete_marker=fi.deleted,
            size=fi.size,
            mod_time=fi.mod_time,
            etag=fi.metadata.get("etag", ""),
            content_type=fi.metadata.get("content-type", "application/octet-stream"),
            user_defined={
                k: v for k, v in fi.metadata.items() if k not in ("etag", "content-type")
            },
            num_versions=fi.num_versions,
        )


class ObjectHandle:
    """Resolved read handle: concrete set + quorum-picked version + per-drive
    metadata, holding the namespace read lock until closed. Constructing
    reads is free; all I/O happens during iteration; the lock is refreshed
    during long streams and released when the last read() iterator finishes
    (or close() is called)."""

    _REFRESH_EVERY = 30.0  # seconds; well under the 120s lock TTL

    def __init__(
        self, es: ErasureSet, bucket: str, obj: str, fi: FileInfo, metas,
        mutex=None, requested_vid: str = "",
    ):
        self.es = es
        self.bucket = bucket
        self.obj = obj
        self.fi = fi
        self.metas = metas
        self._mutex = mutex
        self._vid = requested_vid

    def close(self) -> None:
        mtx, self._mutex = self._mutex, None
        if mtx is not None:
            mtx.runlock()

    def read(
        self, offset: int = 0, length: int = -1, close_when_done: bool = True
    ) -> Iterator[bytes]:
        """Iterator over one byte range. By default the handle (and its
        namespace read lock) closes when this iterator finishes — right
        for the single-read GET path. Callers issuing MULTIPLE reads over
        one handle (e.g. per-part SSE range decode) pass
        close_when_done=False and close() in their own finally, so parts
        2..N still read under the lock."""
        import time as _time

        if length < 0:
            length = self.fi.size - offset
        if offset < 0 or offset + length > self.fi.size:
            self.close()
            raise ValueError("invalid range")

        # full-object reads of eligible hot objects fill the data cache:
        # bytes below already passed per-block bitrot verification, and
        # they enter stamped with THIS read's quorum FileInfo, so the
        # cached copy shares the served copy's etag/bitrot identity.
        # The token rejects the fill if the object was invalidated while
        # streaming (a TTL-expired lock racing an overwrite).
        fill_token = None
        if offset == 0 and length == self.fi.size:
            fill_token = self.es.cache.data_admit(
                self.bucket, self.obj, self._vid, self.fi
            )
        # objects ABOVE the whole-object size gate fill the range-segment
        # tier instead: every stripe block this read fully decodes (and
        # bitrot-verified) is offered per-segment, under the same
        # invalidation-token discipline
        seg_token = None
        if fill_token is None:
            seg_token = self.es.cache.segment_admit(
                self.bucket, self.obj, self._vid, self.fi
            )
        if offset != 0 or length != self.fi.size:
            # feed the sequential-read detector (prefetch plane) with the
            # observed range — misses included, or a run could never form
            self.es.cache.segment_observe(
                self.bucket, self.obj, self._vid, offset, length, self.fi
            )

        seg_sink = None
        if seg_token is not None:
            def seg_sink(pnum: int, bi: int, data) -> None:
                self.es.cache.segment_put(
                    self.bucket, self.obj, self._vid, self.fi, pnum, bi,
                    data, seg_token,
                )

        def gen():
            last_refresh = _time.monotonic()
            collected: list[bytes] | None = [] if fill_token is not None else None
            try:
                for chunk in self.es._read_range(
                    self.bucket, self.obj, self.fi, self.metas, offset,
                    length, seg_sink,
                ):
                    now = _time.monotonic()
                    if self._mutex is not None and now - last_refresh > self._REFRESH_EVERY:
                        self._mutex.refresh()
                        last_refresh = now
                    if collected is not None:
                        # data-cache fill owns its copy (chunks may be
                        # views of per-window assembly buffers)
                        bufpool.count_copy("cache-fill")
                        collected.append(bytes(chunk))
                    yield chunk
                if collected is not None:
                    self.es.cache.data_put(
                        self.bucket, self.obj, self._vid, self.fi,
                        b"".join(collected), fill_token,
                    )
            finally:
                if close_when_done:
                    self.close()

        return gen()


class SegmentCachedObjectHandle:
    """ObjectHandle-compatible view over cached range segments: the
    hinted range is served by slicing immutable verified stripe-block
    snapshots pinned at open time — no namespace lock, no metadata
    fan-out, no shard I/O (same safety argument as CachedObjectHandle:
    invalidation through the choke point removed any overwritten entry
    before the writer returned, and these bytes are pinned). Reads
    OUTSIDE the hinted range (multi-range callers, SSE per-part decode)
    fall back to a real per-read handle so semantics never narrow."""

    def __init__(self, es: ErasureSet, bucket: str, obj: str, vid: str,
                 fi: FileInfo, start: int, length: int, rows):
        self.es = es
        self.bucket = bucket
        self.obj = obj
        self._vid = vid
        self.fi = fi
        self._start = start
        self._length = length
        self._rows = rows  # [(abs_offset, bytes)] covering the range

    def close(self) -> None:
        pass

    def read(
        self, offset: int = 0, length: int = -1, close_when_done: bool = True
    ) -> Iterator[bytes]:
        if length < 0:
            length = self.fi.size - offset
        if offset < 0 or offset + length > self.fi.size:
            raise ValueError("invalid range")
        if offset != 0 or length != self.fi.size:
            self.es.cache.segment_observe(
                self.bucket, self.obj, self._vid, offset, length, self.fi
            )
        if not (
            offset >= self._start
            and offset + length <= self._start + self._length
        ):
            # outside the pinned range: open a real handle for this read
            # (always self-closing — a leaked rlock would outlive us),
            # pinned to THIS handle's version where one exists — a
            # concurrent overwrite must not splice newer bytes into a
            # response whose headers came from self.fi
            vid = self._vid or (self.fi.version_id or "")
            _oi, h = self.es.open_object(self.bucket, self.obj, vid)
            return h.read(offset, length)

        def gen():
            end = offset + length
            for abs_off, data in self._rows:
                if abs_off + len(data) <= offset:
                    continue
                if abs_off >= end:
                    break
                mv = memoryview(data)[
                    max(offset - abs_off, 0) : end - abs_off
                ]
                for o in range(0, len(mv), 1 << 20):
                    yield mv[o : o + (1 << 20)]

        return gen()


class CachedObjectHandle:
    """ObjectHandle-compatible view over a data-cache entry: ranged reads
    slice an immutable in-memory snapshot; there is no namespace lock to
    hold or release (the snapshot cannot be torn by concurrent writers —
    invalidation removed it from the cache before any overwrite
    completed, and this handle pinned the bytes). Serves the hot-GET
    path: no metadata fan-out, no shard I/O, no lock RPCs."""

    def __init__(self, fi: FileInfo, data: bytes):
        self.fi = fi
        self._data = memoryview(data)

    def close(self) -> None:
        pass

    def read(
        self, offset: int = 0, length: int = -1, close_when_done: bool = True
    ) -> Iterator[bytes]:
        if length < 0:
            length = self.fi.size - offset
        if offset < 0 or offset + length > self.fi.size:
            raise ValueError("invalid range")

        def gen():
            mv = self._data[offset:offset + length]
            for o in range(0, len(mv), 1 << 20):
                yield mv[o:o + (1 << 20)]

        return gen()
