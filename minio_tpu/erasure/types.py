"""Object-layer types (ObjectInfo & friends) — the currency between the
erasure layer and the S3 API layer (mirrors ObjectInfo in
/root/reference/cmd/object-api-datatypes.go)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ObjectInfo:
    bucket: str = ""
    name: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    size: int = 0
    mod_time: int = 0  # ns
    etag: str = ""
    content_type: str = ""
    user_defined: dict[str, str] = field(default_factory=dict)
    parts: int = 1
    is_dir: bool = False
    storage_class: str = "STANDARD"
    num_versions: int = 0


@dataclass
class ListObjectsResult:
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)
    is_truncated: bool = False
    next_marker: str = ""
    next_version_marker: str = ""


@dataclass
class BucketInfo:
    name: str
    created: int  # ns
    versioning: bool = False
    object_locking: bool = False
