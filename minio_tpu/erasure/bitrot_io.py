"""Streaming-bitrot shard file format: digest || block, per shard block.

The on-disk format matches the reference's streaming bitrot writer
(/root/reference/cmd/bitrot-streaming.go): a shard file holding K shard
blocks of `shard_size` bytes (last may be short) is stored as
    hash(block_0) || block_0 || hash(block_1) || block_1 || ...
with HighwayHash-256 (32-byte digests, MinIO magic key). Verification reads
recompute each block's digest (/root/reference/cmd/bitrot.go:164-216).

The legacy WHOLE-FILE format (/root/reference/cmd/bitrot-whole.go) is also
supported for reading: the shard file holds raw shard bytes and ONE digest
over the whole file lives in the version metadata
(ErasureInfo.checksums[part].hash). New writes always produce the
streaming format, like the reference; whole-file is a read/verify/heal
compatibility surface for imported legacy data.

FAMILY FRAMING — the shard-block frame depends on the erasure code
family recorded in xl.meta (ErasureInfo.algorithm):

- ``reedsolomon``:  hash(block) || block            (one frame)
- ``cauchy``:       hash(sub1) || sub1 || hash(sub2) || sub2

The cauchy family (ops/cauchy.py) sub-packetizes every shard block into
two sub-chunks so single-shard repair can fetch PARTIAL shards; each
sub-chunk carries its own digest so a sub-chunk ranged read stays
bitrot-verified without touching the other half (``sub_chunk_span`` +
``verify_sub_chunk`` are that read path). Unknown family strings raise
the typed ``errors.UnknownErasureFamily``.
"""

from __future__ import annotations

import os

from ..ops.bitrot import DEFAULT_BITROT_ALGO, BitrotAlgorithm
from ..storage import errors

DIGEST_SIZE = 32

FAMILY_RS = "reedsolomon"
FAMILY_CAUCHY = "cauchy"
FAMILIES = (FAMILY_RS, FAMILY_CAUCHY)


def check_family(family: str) -> str:
    """Validate an xl.meta code-family string; single choke point for the
    'unknown-family is a typed error, never a misread frame' contract."""
    if family not in FAMILIES:
        raise errors.UnknownErasureFamily(
            f"unknown erasure code family {family!r} (known: {FAMILIES})"
        )
    return family


def frames_per_block(family: str = FAMILY_RS) -> int:
    """Bitrot frames (digests) per shard block for a code family."""
    return 2 if check_family(family) == FAMILY_CAUCHY else 1


def sub_lens(shard_size: int) -> tuple[int, int]:
    """(len(sub-chunk 1), len(sub-chunk 2)) of a sub-packetized shard
    block. Single source: ops/cauchy.sub_lens (floor half first) —
    duplicated arithmetic here would let the framing drift from the
    codec."""
    from ..ops.cauchy import sub_lens as _cs

    return _cs(shard_size)


_sub_lens = sub_lens


def block_offset(shard_size: int, block_index: int, family: str = FAMILY_RS) -> int:
    """Shard-file offset of block `block_index` (its digest(s) included)."""
    return block_index * (
        frames_per_block(family) * DIGEST_SIZE + shard_size
    )


def block_disk_size(shard_size: int, family: str = FAMILY_RS) -> int:
    """On-disk bytes of one shard-block frame group."""
    return frames_per_block(family) * DIGEST_SIZE + shard_size


def sub_chunk_in_block(shard_size: int, which: int) -> tuple[int, int]:
    """(offset within the block's frame group, data length) of one
    sub-chunk frame — the single source for the cauchy frame layout
    that the partial-repair readers (GET + heal) and ``sub_chunk_span``
    all share. ``shard_size`` is THIS block's shard length (tail blocks
    differ from full blocks)."""
    h1, h2 = _sub_lens(shard_size)
    if which == 0:
        return 0, h1
    if which == 1:
        return DIGEST_SIZE + h1, h2
    raise ValueError("sub-chunk index must be 0 or 1")


def sub_chunk_span(
    shard_size: int, block_index: int, which: int, family: str = FAMILY_CAUCHY
) -> tuple[int, int, int]:
    """(file offset, on-disk length, data length) of one sub-chunk frame
    of a cauchy shard block in a uniform-geometry shard file."""
    if check_family(family) != FAMILY_CAUCHY:
        raise ValueError("sub-chunk reads exist only for sub-packetized families")
    base = block_offset(shard_size, block_index, family)
    rel, dlen = sub_chunk_in_block(shard_size, which)
    return base + rel, DIGEST_SIZE + dlen, dlen


def _digest(block: bytes, algo: BitrotAlgorithm) -> bytes:
    if algo in (BitrotAlgorithm.HIGHWAYHASH256, BitrotAlgorithm.HIGHWAYHASH256S):
        from ..ops.bitrot import fast_hash256

        return fast_hash256(block)
    h = algo.new()
    h.update(block)
    return h.digest()


def frame_block(
    block: bytes, family: str = FAMILY_RS,
    algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO,
) -> bytes:
    """Digest-frame one shard block for its family's on-disk format."""
    if check_family(family) == FAMILY_CAUCHY:
        h1, _h2 = _sub_lens(len(block))
        sub1, sub2 = block[:h1], block[h1:]
        return _digest(sub1, algo) + sub1 + _digest(sub2, algo) + sub2
    return _digest(block, algo) + block


def verify_block(
    buf: bytes, expect_len: int, algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO,
    family: str = FAMILY_RS, view: bool = False,
):
    """Split one shard-block frame group and verify it; returns the block.

    Raises FileCorrupt on short reads or digest mismatch — the bitrot
    detection that triggers healing in the read path. Single source of
    truth for the record layout (used by reads, inline verify, heal).
    For the cauchy family the buffer holds TWO digest||sub-chunk frames;
    both verify and the sub-chunks concatenate back into the block.

    ``view=True`` returns a zero-copy memoryview of the payload where
    the frame layout allows (reedsolomon: the payload is one contiguous
    span of ``buf``, which must stay alive while the view is used). The
    cauchy frame interleaves digests between its sub-chunks, so a
    contiguous block always assembles once into a fresh buffer —
    regardless of ``view``, that one copy is inherent to the format."""
    if check_family(family) == FAMILY_CAUCHY:
        if len(buf) != 2 * DIGEST_SIZE + expect_len:
            raise errors.FileCorrupt("short shard block")
        h1, h2 = _sub_lens(expect_len)
        mv = memoryview(buf)
        sub1 = verify_sub_chunk(mv[: DIGEST_SIZE + h1], h1, algo)
        sub2 = verify_sub_chunk(mv[DIGEST_SIZE + h1 :], h2, algo)
        out = bytearray(expect_len)
        out[:h1] = sub1
        out[h1:] = sub2
        return out
    if len(buf) != DIGEST_SIZE + expect_len:
        raise errors.FileCorrupt("short shard block")
    mv = memoryview(buf)
    digest, block = mv[:DIGEST_SIZE], mv[DIGEST_SIZE:]
    if _digest(block, algo) != digest:
        raise errors.FileCorrupt("bitrot detected")
    return block if view else bytes(block)


def verify_sub_chunk(
    buf: bytes, expect_len: int, algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO
) -> bytes:
    """Verify one digest||sub-chunk frame (the partial-repair read unit)."""
    if len(buf) != DIGEST_SIZE + expect_len:
        raise errors.FileCorrupt("short sub-chunk frame")
    digest, sub = buf[:DIGEST_SIZE], buf[DIGEST_SIZE:]
    if _digest(sub, algo) != digest:
        raise errors.FileCorrupt("bitrot detected (sub-chunk)")
    return sub


def whole_file_digest(data: bytes, algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO) -> bytes:
    """Digest of a whole raw shard file (legacy whole-file bitrot mode)."""
    if algo in (BitrotAlgorithm.HIGHWAYHASH256, BitrotAlgorithm.HIGHWAYHASH256S):
        from ..ops.bitrot import fast_hash256

        return fast_hash256(data)
    h = algo.new()
    h.update(data)
    return h.digest()


def verify_whole_file(
    data: bytes, expect_digest: bytes,
    algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO,
) -> bytes:
    """Verify a whole raw shard against its stored metadata digest
    (reference cmd/bitrot-whole.go wholeBitrotVerifier)."""
    if whole_file_digest(data, algo) != expect_digest:
        raise errors.FileCorrupt("bitrot detected (whole-file)")
    return data


def bitrot_verify_file(
    path: str,
    want_file_size: int,
    shard_size: int,
    algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO,
    family: str = FAMILY_RS,
) -> None:
    """Whole-file streaming verification (heal/scanner path).

    want_file_size is the *data* size of the shard (without digests); the
    on-disk file must be exactly want_file_size plus the family's digest
    overhead (one 32-byte digest per frame, frames_per_block per block).
    """
    frames = frames_per_block(family)
    n_blocks = -(-want_file_size // shard_size) if want_file_size else 0
    expect_disk = want_file_size + n_blocks * frames * DIGEST_SIZE
    try:
        actual = os.path.getsize(path)
    except FileNotFoundError:
        raise errors.FileNotFound(path) from None
    if actual != expect_disk:
        raise errors.FileCorrupt(
            f"shard file size {actual} != expected {expect_disk}"
        )
    with open(path, "rb") as f:
        left = want_file_size
        while left > 0:
            n = min(shard_size, left)
            buf = f.read(frames * DIGEST_SIZE + n)
            if len(buf) != frames * DIGEST_SIZE + n:
                raise errors.FileCorrupt("short read during verify")
            verify_block(buf, n, algo, family)
            left -= n
