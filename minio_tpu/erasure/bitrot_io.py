"""Streaming-bitrot shard file format: digest || block, per shard block.

The on-disk format matches the reference's streaming bitrot writer
(/root/reference/cmd/bitrot-streaming.go): a shard file holding K shard
blocks of `shard_size` bytes (last may be short) is stored as
    hash(block_0) || block_0 || hash(block_1) || block_1 || ...
with HighwayHash-256 (32-byte digests, MinIO magic key). Verification reads
recompute each block's digest (/root/reference/cmd/bitrot.go:164-216).

The legacy WHOLE-FILE format (/root/reference/cmd/bitrot-whole.go) is also
supported for reading: the shard file holds raw shard bytes and ONE digest
over the whole file lives in the version metadata
(ErasureInfo.checksums[part].hash). New writes always produce the
streaming format, like the reference; whole-file is a read/verify/heal
compatibility surface for imported legacy data.
"""

from __future__ import annotations

import os

from ..ops.bitrot import DEFAULT_BITROT_ALGO, BitrotAlgorithm
from ..storage import errors

DIGEST_SIZE = 32


def block_offset(shard_size: int, block_index: int) -> int:
    """Shard-file offset of block `block_index` (its digest included)."""
    return block_index * (DIGEST_SIZE + shard_size)


def verify_block(
    buf: bytes, expect_len: int, algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO
) -> bytes:
    """Split one digest||block record and verify it; returns the block.

    Raises FileCorrupt on short reads or digest mismatch — the bitrot
    detection that triggers healing in the read path. Single source of
    truth for the record layout (used by reads, inline verify, heal)."""
    if len(buf) != DIGEST_SIZE + expect_len:
        raise errors.FileCorrupt("short shard block")
    digest, block = buf[:DIGEST_SIZE], buf[DIGEST_SIZE:]
    if algo in (BitrotAlgorithm.HIGHWAYHASH256, BitrotAlgorithm.HIGHWAYHASH256S):
        from ..ops.bitrot import fast_hash256

        got = fast_hash256(block)
    else:
        h = algo.new()
        h.update(block)
        got = h.digest()
    if got != digest:
        raise errors.FileCorrupt("bitrot detected")
    return block


def whole_file_digest(data: bytes, algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO) -> bytes:
    """Digest of a whole raw shard file (legacy whole-file bitrot mode)."""
    if algo in (BitrotAlgorithm.HIGHWAYHASH256, BitrotAlgorithm.HIGHWAYHASH256S):
        from ..ops.bitrot import fast_hash256

        return fast_hash256(data)
    h = algo.new()
    h.update(data)
    return h.digest()


def verify_whole_file(
    data: bytes, expect_digest: bytes,
    algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO,
) -> bytes:
    """Verify a whole raw shard against its stored metadata digest
    (reference cmd/bitrot-whole.go wholeBitrotVerifier)."""
    if whole_file_digest(data, algo) != expect_digest:
        raise errors.FileCorrupt("bitrot detected (whole-file)")
    return data


def bitrot_verify_file(
    path: str,
    want_file_size: int,
    shard_size: int,
    algo: BitrotAlgorithm = DEFAULT_BITROT_ALGO,
) -> None:
    """Whole-file streaming verification (heal/scanner path).

    want_file_size is the *data* size of the shard (without digests); the
    on-disk file must be exactly want_file_size + n_blocks*32.
    """
    n_blocks = -(-want_file_size // shard_size) if want_file_size else 0
    expect_disk = want_file_size + n_blocks * DIGEST_SIZE
    try:
        actual = os.path.getsize(path)
    except FileNotFoundError:
        raise errors.FileNotFound(path) from None
    if actual != expect_disk:
        raise errors.FileCorrupt(
            f"shard file size {actual} != expected {expect_disk}"
        )
    with open(path, "rb") as f:
        left = want_file_size
        while left > 0:
            n = min(shard_size, left)
            buf = f.read(DIGEST_SIZE + n)
            if len(buf) != DIGEST_SIZE + n:
                raise errors.FileCorrupt("short read during verify")
            verify_block(buf, n, algo)
            left -= n
