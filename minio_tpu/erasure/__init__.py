"""Erasure layer (L2/L3): striping, quorum, object semantics, healing."""
