"""ServerPools — the top-level ObjectLayer over one or more pools.

Mirrors /root/reference/cmd/erasure-server-pool.go: new objects land in
the pool with the most free space; reads/deletes fan out to find the pool
that holds the object; buckets exist on every pool. Each pool is an
ErasureSets. This is the object the S3 server programs against.
"""

from __future__ import annotations

from typing import Iterator

from ..storage.datatypes import FileInfo
from ..storage.errors import StorageError
from ..storage.interface import StorageAPI
from .quorum import ErasureError, ObjectNotFound, VersionNotFound
from .sets import ErasureSets
from .types import BucketInfo, ObjectInfo


class ServerPools:
    def __init__(self, pools: list[ErasureSets]):
        from ..placement import PlacementPolicy

        if not pools:
            raise ValueError("need at least one pool")
        self.pools = pools
        # pool indexes currently decommissioning (set by PoolManager):
        # NEW objects never land there, or the drain would chase live
        # writes forever. Indexes are re-stamped by topology.remove_pool.
        self.draining: set[int] = set()
        # placement policy engine (placement/policy.py): consulted for
        # every NEW object's pool; rules persist through this store
        self.placement = PlacementPolicy(self)

    # facade plumbing for listing/multipart
    @property
    def disks(self) -> list[StorageAPI]:
        return [d for p in self.pools for d in p.disks]

    @property
    def n(self) -> int:
        return self.pools[0].n

    @property
    def default_parity(self) -> int:
        return self.pools[0].default_parity

    # -- placement ---------------------------------------------------------

    def _pool_with_most_free(self) -> ErasureSets:
        if len(self.pools) == 1:
            return self.pools[0]
        draining = self.draining if len(self.draining) < len(self.pools) else set()
        best, best_free = self.pools[0], -1
        for i, p in enumerate(self.pools):
            if i in draining:
                continue  # a decommissioning pool takes no new objects
            free = 0
            for d in p.disks:
                try:
                    free += d.disk_info().free
                except (StorageError, OSError):
                    pass  # offline drive contributes no free space
            if free > best_free:
                best, best_free = p, free
        return best

    def _placement_pool(self, bucket: str, obj: str) -> ErasureSets:
        """Pool for a NEW object: the placement engine's decision
        (pin/spread rules, weight-by-free-space default), falling back to
        the legacy most-free heuristic when placement is off or the key
        is in the system namespace (whose writes include the engine's own
        rule persistence — they must never re-enter it)."""
        from ..placement import placement_enabled

        if len(self.pools) == 1:
            return self.pools[0]
        if bucket.startswith(".minio.sys"):
            # system namespace anchors on pool 0: IAM docs, placement
            # rules, and decommission checkpoints must never land on a
            # pool that can be decommissioned and detached (remove_pool
            # refuses pool 0); also breaks the recursion the placement
            # engine's own rule persistence would otherwise cause
            return self.pools[0]
        if not placement_enabled():
            return self._pool_with_most_free()
        idx = self.placement.pool_index_for(bucket, obj)
        if 0 <= idx < len(self.pools):
            return self.pools[idx]
        return self._pool_with_most_free()

    def _pool_holding(self, bucket: str, obj: str, version_id: str = "") -> ErasureSets:
        """Pool that already has the object (parallel lookup in the
        reference, getPoolInfoExistingWithOpts); raises ObjectNotFound."""
        last: Exception = ObjectNotFound(f"{bucket}/{obj}")
        for p in self.pools:
            try:
                p.get_object_info(bucket, obj, version_id)
                return p
            except (ObjectNotFound, VersionNotFound) as e:
                last = e
        raise last

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        for p in self.pools:
            p.make_bucket(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        for p in self.pools:
            # miniovet: ignore[coherence-path] -- delegates per pool inside
            # the loop (self.pools is never empty); every ErasureSet
            # underneath invalidates its own cache in its locked region
            p.delete_bucket(bucket, force=force)

    def bucket_exists(self, bucket: str) -> bool:
        return any(p.bucket_exists(bucket) for p in self.pools)

    def list_buckets(self) -> list[BucketInfo]:
        return self.pools[0].list_buckets()

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, obj: str, data: bytes, *a, **kw) -> ObjectInfo:
        # overwrite in place if some pool already holds the object; new
        # objects land where the placement engine says
        if len(self.pools) > 1:
            try:
                pool = self._pool_holding(bucket, obj)
            except (ObjectNotFound, VersionNotFound):
                pool = self._placement_pool(bucket, obj)
        else:
            pool = self.pools[0]
        return pool.put_object(bucket, obj, data, *a, **kw)

    def get_object(self, bucket: str, obj: str, version_id: str = "", *a, **kw):
        return self._pool_holding(bucket, obj, version_id).get_object(
            bucket, obj, version_id, *a, **kw
        )

    def open_object(self, bucket: str, obj: str, version_id: str = "",
                    range_hint=None):
        # the returned handle is bound to the concrete set that holds the
        # object — later reads never re-resolve pools
        return self._pool_holding(bucket, obj, version_id).open_object(
            bucket, obj, version_id, range_hint
        )

    def get_object_info(self, bucket: str, obj: str, version_id: str = "") -> ObjectInfo:
        return self._pool_holding(bucket, obj, version_id).get_object_info(
            bucket, obj, version_id
        )

    def delete_object(
        self, bucket: str, obj: str, version_id: str = "", versioned: bool = False, **kw
    ) -> ObjectInfo:
        try:
            pool = self._pool_holding(bucket, obj, version_id)
        except (ObjectNotFound, VersionNotFound):
            if versioned:
                # delete marker still gets written somewhere deterministic
                pool = self.pools[0]
            else:
                raise
        return pool.delete_object(bucket, obj, version_id, versioned=versioned, **kw)

    def list_object_versions(self, bucket: str, obj: str) -> list[ObjectInfo]:
        out: list[ObjectInfo] = []
        for p in self.pools:
            try:
                out.extend(p.list_object_versions(bucket, obj))
            except (ErasureError, StorageError, OSError):
                pass  # pool doesn't hold the object (or is offline)
        out.sort(key=lambda o: o.mod_time, reverse=True)
        return out

    def heal_object(self, bucket: str, obj: str, version_id: str = "") -> dict:
        return self._pool_holding(bucket, obj, version_id).heal_object(
            bucket, obj, version_id
        )

    def walk_objects(self, bucket: str, prefix: str = "") -> Iterator[str]:
        for p in self.pools:
            yield from p.walk_objects(bucket, prefix)

    def set_object_tags(self, bucket, obj, tags, version_id=""):
        return self._pool_holding(bucket, obj, version_id).set_object_tags(
            bucket, obj, tags, version_id
        )

    def transition_object(self, bucket, obj, tier, remote_key, version_id="", restub=False):
        return self._pool_holding(bucket, obj, version_id).transition_object(
            bucket, obj, tier, remote_key, version_id, restub
        )

    def restore_object(self, bucket, obj, data, days, version_id=""):
        return self._pool_holding(bucket, obj, version_id).restore_object(
            bucket, obj, data, days, version_id
        )

    def update_object_metadata(self, bucket, obj, version_id, mutate):
        return self._pool_holding(bucket, obj, version_id).update_object_metadata(
            bucket, obj, version_id, mutate
        )

    def get_object_tags(self, bucket, obj, version_id=""):
        return self._pool_holding(bucket, obj, version_id).get_object_tags(
            bucket, obj, version_id
        )
