"""Multipart uploads: each part an independent erasure stream, stitched by
metadata only at completion.

Mirrors /root/reference/cmd/erasure-multipart.go: uploads live under the
system volume (getUploadIDDir, :47); PutObjectPart erasure-codes each part
(:575); CompleteMultipartUpload moves part shard files into the final
object's data dir and writes one xl.meta whose parts[] stitches them
(:1096) — part data is never re-encoded or rewritten.
"""

from __future__ import annotations

import hashlib
import uuid
from dataclasses import dataclass

from ..storage import errors
from ..storage.datatypes import FileInfo, ObjectPartInfo, now_ns
from ..utils.hashing import hash_order
from .quorum import (
    ObjectNotFound,
    QuorumError,
    VersionNotFound,
    reduce_quorum_errs,
)
from .set import ErasureSet, _lock_dyn
from .types import ObjectInfo

MP_VOLUME = ".minio.sys/multipart"
POOL_SEP = "~"  # upload ids are "<pool_idx>~<uuid>" so every part/complete
# call resolves to the pool (and thus set) that started the upload


class UploadNotFound(Exception):
    pass


class InvalidPart(Exception):
    pass


class InvalidPartOrder(Exception):
    pass


@dataclass
class PartRecord:
    number: int
    etag: str
    size: int
    mod_time: int


class MultipartManager:
    def __init__(self, es: ErasureSet, part_transform=None):
        self.es = es
        self.part_transform = part_transform

    def _upload_key(self, bucket: str, obj: str, upload_id: str) -> str:
        return f"{bucket}/{obj}/uploads/{upload_id}"

    def _part_key(self, bucket: str, obj: str, upload_id: str, n: int) -> str:
        return f"{self._upload_key(bucket, obj, upload_id)}/part-meta/{n:05d}"

    # -- lifecycle -----------------------------------------------------------

    def new_upload(
        self,
        bucket: str,
        obj: str,
        user_defined: dict[str, str] | None = None,
        parity: int | None = None,
        family: str | None = None,
    ) -> str:
        if not self.es.bucket_exists(bucket):
            from .quorum import BucketNotFound

            raise BucketNotFound(bucket)
        upload_id = str(uuid.uuid4())
        meta = dict(user_defined or {})
        meta["__distribution"] = ",".join(
            str(x) for x in hash_order(f"{bucket}/{obj}", self.es.n)
        )
        if parity is not None:
            meta["__parity"] = str(parity)
        # the upload's code family pins at initiation (like parity and
        # distribution): every part must share the final object's shard
        # format, even if MINIO_TPU_EC_FAMILY changes mid-upload
        from .coder import default_ec_family

        meta["__family"] = family or default_ec_family()
        self.es.put_object(
            MP_VOLUME,
            self._upload_key(bucket, obj, upload_id),
            b"",
            user_defined=meta,
        )
        return upload_id

    def _upload_meta(self, bucket: str, obj: str, upload_id: str) -> ObjectInfo:
        try:
            return self.es.get_object_info(
                MP_VOLUME, self._upload_key(bucket, obj, upload_id)
            )
        except ObjectNotFound:
            raise UploadNotFound(upload_id) from None

    def put_part(
        self, bucket: str, obj: str, upload_id: str, part_number: int, data: bytes,
        extra_meta: dict[str, str] | None = None,
        transform_ctx=None,
    ) -> str:
        if not 1 <= part_number <= 10000:
            raise InvalidPart(f"part number {part_number}")
        up = self._upload_meta(bucket, obj, upload_id)
        dist = [int(x) for x in up.user_defined["__distribution"].split(",")]
        parity = int(up.user_defined.get("__parity", self.es.default_parity))
        # absent __family (upload initiated before the family field
        # existed) can ONLY mean its earlier parts were framed
        # reedsolomon — falling back to the CURRENT default here would
        # mix shard formats inside one object if the knob flipped
        # mid-upload across a restart
        family = up.user_defined.get("__family") or "reedsolomon"
        part_meta: dict[str, str] | None = dict(extra_meta) if extra_meta else None
        plain_after = None  # streamed transforms know the size only at EOF
        if self.part_transform is not None:
            transformed = self.part_transform(
                bucket, obj, up.user_defined, part_number, data, transform_ctx
            )
            if transformed is not None:
                data, plain = transformed
                if callable(plain):
                    plain_after = plain
                else:
                    part_meta = {**(part_meta or {}), "__plain_size": str(plain)}
        pkey = self._part_key(bucket, obj, upload_id, part_number)
        oi = self.es.put_object(
            MP_VOLUME,
            pkey,
            data,  # bytes or a chunk iterator (streamed parts)
            user_defined=part_meta,
            parity=parity,
            distribution=dist,
            allow_inline=False,
            family=family,
        )
        if plain_after is not None:
            size = str(plain_after())
            self.es.update_object_metadata(
                MP_VOLUME, pkey, "",
                lambda md: md.__setitem__("__plain_size", size),
            )
        return oi.etag

    def update_part_metadata(
        self, bucket: str, obj: str, upload_id: str, part_number: int,
        extra: dict[str, str],
    ) -> None:
        """Post-upload part metadata merge (streamed trailer checksums)."""
        pkey = self._part_key(bucket, obj, upload_id, part_number)
        self.es.update_object_metadata(
            MP_VOLUME, pkey, "", lambda md: md.update(extra)
        )

    def list_parts(
        self, bucket: str, obj: str, upload_id: str, max_parts: int = 1000,
        part_marker: int = 0,
    ) -> tuple[list[PartRecord], bool]:
        """Parts after part_marker, plus whether more remain (the S3
        IsTruncated contract, reference cmd/erasure-multipart.go
        ListObjectParts)."""
        self._upload_meta(bucket, obj, upload_id)
        if max_parts <= 0:
            # mirror the reference: maxParts==0 is an empty, NON-truncated
            # page (a truncated page with no next marker cannot progress)
            return [], False
        from . import listing

        # marker walk: part names are zero-padded so the lexicographic
        # listing order IS part-number order; fetch one extra to learn
        # whether the page is truncated
        base = f"{self._upload_key(bucket, obj, upload_id)}/part-meta/"
        res = listing.list_objects(
            self.es,
            MP_VOLUME,
            prefix=base,
            marker=f"{base}{part_marker:05d}" if part_marker else "",
            max_keys=max_parts + 1,
        )
        out = [
            PartRecord(
                int(o.name.rsplit("/", 1)[-1]), o.etag, o.size, o.mod_time
            )
            for o in res.objects
        ]
        return out[:max_parts], len(out) > max_parts

    def list_uploads(self, bucket: str, prefix: str = "") -> list[tuple[str, str]]:
        """[(object_key, upload_id)] of in-progress uploads."""
        from . import listing

        res = listing.list_objects(
            self.es, MP_VOLUME, prefix=f"{bucket}/{prefix}", max_keys=10000
        )
        out = []
        for o in res.objects:
            parts = o.name.split("/uploads/")
            if len(parts) == 2 and "/" not in parts[1]:
                out.append((parts[0][len(bucket) + 1 :], parts[1]))
        return out

    def abort(self, bucket: str, obj: str, upload_id: str) -> None:
        self._upload_meta(bucket, obj, upload_id)
        self._cleanup(bucket, obj, upload_id)

    def _cleanup(self, bucket: str, obj: str, upload_id: str) -> None:
        prefix = self._upload_key(bucket, obj, upload_id)
        for disk in self.es.disks:
            try:
                disk.delete(MP_VOLUME, prefix, recursive=True)
            except Exception:  # noqa: BLE001
                pass
        # recursive delete bypassed delete_object: drop every cached
        # upload/part record under the prefix through the choke point
        self.es.cache.invalidate_prefix(MP_VOLUME, prefix)

    # -- completion ------------------------------------------------------------

    def complete(
        self,
        bucket: str,
        obj: str,
        upload_id: str,
        parts: list[tuple[int, str]],
        versioned: bool = False,
        part_checksums: dict[int, dict[str, str]] | None = None,
        check_precond=None,
    ) -> ObjectInfo:
        """Stitch uploaded parts into the final object (metadata only).

        part_checksums: client-supplied per-part x-amz-checksum values from
        the CompleteMultipartUpload XML — verified against the stored part
        checksums, then folded into the composite object checksum
        (reference internal/hash/checksum.go composite semantics)."""
        from ..utils import checksum as cks

        up = self._upload_meta(bucket, obj, upload_id)
        dist = [int(x) for x in up.user_defined["__distribution"].split(",")]
        parity = int(up.user_defined.get("__parity", self.es.default_parity))
        if not parts:
            raise InvalidPart("no parts listed")
        if parts != sorted(parts, key=lambda t: t[0]) or len(
            {n for n, _ in parts}
        ) != len(parts):
            raise InvalidPartOrder("parts must be ascending and unique")

        # resolve each listed part's stored metadata (quorum)
        part_fis: list[FileInfo] = []
        md5_concat = b""
        total = 0
        for n, etag in parts:
            try:
                pfi, _, _, _ = self.es._quorum_fileinfo(
                    MP_VOLUME, self._part_key(bucket, obj, upload_id, n), "", False
                )
            except Exception:
                raise InvalidPart(f"part {n} not found") from None
            stored_etag = pfi.metadata.get("etag", "")
            if etag.strip('"') != stored_etag:
                raise InvalidPart(f"part {n} etag mismatch")
            for algo, want in (part_checksums or {}).get(n, {}).items():
                stored = pfi.metadata.get(f"{cks.META_PREFIX}{algo}")
                # AWS rejects a checksum member the part wasn't uploaded
                # with — silence here would defeat client-side validation
                if stored is None or stored != want:
                    raise InvalidPart(f"part {n} {algo} checksum mismatch")
            part_fis.append(pfi)
            md5_concat += bytes.fromhex(stored_etag)
            total += pfi.size

        # composite checksums over algorithms stored on EVERY part
        # (CRC64NVME is full-object-only per AWS — no "-N" composite form
        # exists for it, so it stays per-part metadata only)
        composite_meta: dict[str, str] = {}
        part_cks_record: dict[str, dict[str, str]] = {}
        for algo in cks.COMPOSITE_ALGOS:
            vals = [
                pfi.metadata.get(f"{cks.META_PREFIX}{algo}") for pfi in part_fis
            ]
            if all(v is not None for v in vals):
                composite_meta[f"{cks.META_PREFIX}{algo}"] = cks.composite(
                    algo, vals  # type: ignore[arg-type]
                )
                for (n, _), v in zip(parts, vals):
                    part_cks_record.setdefault(str(n), {})[algo] = v  # type: ignore[arg-type]

        final_etag = hashlib.md5(md5_concat).hexdigest() + f"-{len(parts)}"
        fi = FileInfo(volume=bucket, name=obj)
        fi.version_id = str(uuid.uuid4()) if versioned else ""
        fi.mod_time = now_ns()
        fi.size = total
        fi.data_dir = str(uuid.uuid4())
        fi.metadata = {
            k: v for k, v in up.user_defined.items() if not k.startswith("__")
        }
        fi.metadata["etag"] = final_etag
        fi.metadata.update(composite_meta)
        if part_cks_record:
            import json as _cks_json

            fi.metadata[cks.PART_CHECKSUMS_META] = _cks_json.dumps(part_cks_record)
        from ..crypto import sse as ssemod

        if ssemod.META_ALGO in fi.metadata:
            # per-part plaintext sizes: the decode path maps ranges to the
            # overlapping parts' packet streams
            import json as _json

            sizes = [
                [n, int(pfi.metadata.get("__plain_size", pfi.size))]
                for (n, _), pfi in zip(parts, part_fis)
            ]
            fi.metadata[ssemod.META_PART_SIZES] = _json.dumps(sizes)
            fi.metadata[ssemod.META_ACTUAL_SIZE] = str(sum(s for _, s in sizes))
        fi.erasure = part_fis[0].erasure
        fi.erasure.distribution = dist
        fi.erasure.parity_blocks = parity
        fi.erasure.data_blocks = self.es.n - parity
        fi.parts = [
            ObjectPartInfo(n, pfi.size, pfi.size, pfi.mod_time, pfi.metadata.get("etag", ""))
            for (n, _), pfi in zip(parts, part_fis)
        ]

        # the final commit must exclude concurrent put/delete of the same
        # object (same namespace write lock put_object takes)
        mtx = self.es.ns.new(bucket, obj)
        # same adaptive deadline as put_object: under contention both
        # planes loosen together (and both feed the estimator)
        if not _lock_dyn(mtx, write=True):
            # server-side contention is retryable, not a client error
            raise QuorumError(f"namespace lock timeout completing {bucket}/{obj}")
        if check_precond is not None:
            # conditional completes (If-None-Match/If-Match on
            # CompleteMultipartUpload) evaluate under the same lock as the
            # commit — identical discipline to put_object's hook
            try:
                try:
                    cfi, _, _, _ = self.es._quorum_fileinfo(
                        bucket, obj, "", read_data=False
                    )
                    cur = None if cfi.deleted else self.es._to_object_info(
                        bucket, obj, cfi
                    )
                except (ObjectNotFound, VersionNotFound,
                        errors.FileNotFound, errors.FileVersionNotFound):
                    cur = None  # genuinely absent: precondition sees None
                    # (quorum/storage failures PROPAGATE — a conditional
                    # complete must not treat an unreadable object as
                    # absent and overwrite it)
                check_precond(cur)
            except BaseException:
                mtx.unlock()
                raise

        def commit(i: int, disk) -> None:
            shard_idx = dist[i] - 1
            # move each part's shard file into the final object layout
            for (n, _), pfi in zip(parts, part_fis):
                if mtx.lost:
                    # zombie-holder guard: a committer whose lock was lost
                    # must not rename stale shards over a concurrent write
                    raise QuorumError(
                        f"lock on {bucket}/{obj} lost mid-commit; aborting"
                    )
                src = (
                    f"{self._part_key(bucket, obj, upload_id, n)}/"
                    f"{pfi.data_dir}/part.1"
                )
                disk.rename_file(
                    MP_VOLUME, src, bucket, f"{obj}/{fi.data_dir}/part.{n}"
                )
            dfi = FileInfo.from_dict(fi.to_dict())
            dfi.volume, dfi.name = bucket, obj
            dfi.erasure.index = shard_idx + 1
            disk.write_metadata(bucket, obj, dfi)

        try:
            mtx.start_refresher(write=True)  # 10k-part commits can run long
            futs = [
                self.es._pool.submit(commit, i, disk)
                for i, disk in enumerate(self.es.disks)
            ]
            errs: list[Exception | None] = []
            for f in futs:
                try:
                    f.result()
                    errs.append(None)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
            d = self.es.n - parity
            write_q = d + 1 if d == parity else d
            reduce_quorum_errs(errs, write_q)
        finally:
            mtx.unlock()
        # the commit replaced the live version: write-through invalidation
        # outside the lock (the cross-node broadcast must not inflate
        # lock hold), before the complete response returns
        self.es.cache.invalidate_object(bucket, obj)
        self._cleanup(bucket, obj, upload_id)
        oi = self.es._to_object_info(bucket, obj, fi)
        oi.parts = len(parts)
        return oi


class MultipartRouter:
    """Routes multipart calls through pools -> hashed set.

    The reference routes by getHashedSet(object)
    (/root/reference/cmd/erasure-sets.go NewMultipartUpload); across pools
    the pool index rides inside the upload id so an upload stays pinned to
    the pool that started it (the reference tracks this server-side).
    """

    def __init__(self, store, part_transform=None):
        self.store = store  # ServerPools or anything with .pools/.get_hashed_set
        # optional hook(bucket, obj, upload_meta, part#, data, ctx) ->
        # (stored_bytes, plain_size) | None — the server wires SSE here;
        # ctx carries per-request state (SSE-C customer key headers)
        self.part_transform = part_transform

    def _pools(self):
        return getattr(self.store, "pools", [self.store])

    def _mgr(self, obj: str, pool_idx: int) -> MultipartManager:
        pools = self._pools()
        if not 0 <= pool_idx < len(pools):
            raise UploadNotFound(f"bad pool index {pool_idx}")
        pool = pools[pool_idx]
        # plain ErasureSet stores have no set routing
        es = pool.get_hashed_set(obj) if hasattr(pool, "get_hashed_set") else pool
        return MultipartManager(es, part_transform=self.part_transform)

    @staticmethod
    def _split(upload_id: str) -> tuple[int, str]:
        if POOL_SEP in upload_id:
            head, raw = upload_id.split(POOL_SEP, 1)
            try:
                return int(head), raw
            except ValueError:
                pass
        return 0, upload_id

    def new_upload(
        self, bucket, obj, user_defined=None, parity=None, family=None
    ) -> str:
        pools = self._pools()
        pool_idx = 0
        if len(pools) > 1:
            # a multipart overwrite must land in the pool already holding
            # the object, like put_object does — otherwise reads keep
            # serving the stale copy from the earlier pool
            try:
                pool_idx = pools.index(self.store._pool_holding(bucket, obj))
            except (ObjectNotFound, ValueError):
                # new object (or holder not in this router's pool list):
                # the placement engine decides (pin/spread rules,
                # weight-by-free-space default)
                pool_idx = pools.index(
                    self.store._placement_pool(bucket, obj)
                )
        raw = self._mgr(obj, pool_idx).new_upload(
            bucket, obj, user_defined, parity, family
        )
        return f"{pool_idx}{POOL_SEP}{raw}"

    def put_part(self, bucket, obj, upload_id, part_number, data,
                 extra_meta=None, transform_ctx=None) -> str:
        pidx, raw = self._split(upload_id)
        return self._mgr(obj, pidx).put_part(
            bucket, obj, raw, part_number, data, extra_meta, transform_ctx
        )

    def update_part_metadata(self, bucket, obj, upload_id, part_number, extra):
        pidx, raw = self._split(upload_id)
        return self._mgr(obj, pidx).update_part_metadata(
            bucket, obj, raw, part_number, extra
        )

    def list_parts(self, bucket, obj, upload_id, max_parts=1000, part_marker=0):
        pidx, raw = self._split(upload_id)
        return self._mgr(obj, pidx).list_parts(bucket, obj, raw, max_parts, part_marker)

    def abort(self, bucket, obj, upload_id) -> None:
        pidx, raw = self._split(upload_id)
        self._mgr(obj, pidx).abort(bucket, obj, raw)

    def complete(self, bucket, obj, upload_id, parts, versioned=False,
                 part_checksums=None, check_precond=None):
        pidx, raw = self._split(upload_id)
        return self._mgr(obj, pidx).complete(
            bucket, obj, raw, parts, versioned, part_checksums, check_precond
        )

    def list_uploads(self, bucket, prefix="") -> list[tuple[str, str]]:
        out = []
        for pidx, pool in enumerate(self._pools()):
            sets = getattr(pool, "sets", [pool])
            for s in sets:
                for key, raw in MultipartManager(s).list_uploads(bucket, prefix):
                    out.append((key, f"{pidx}{POOL_SEP}{raw}"))
        return sorted(set(out))
