"""ErasureCoder — routes stripe blocks to the right codec backend.

TPU-first split: every FULL stripe block of an object has the same shape
([d, ceil(block_size/d)]), so all full blocks batch into fixed-shape fused
encode+hash device dispatches (ops/rs_jax.py + ops/bitrot_jax.py — no
recompilation). Only the object's final partial block has a variable shard
size; it runs on the numpy codec (ops/rs.py + ops/highwayhash.py), which is
byte-identical. GetObject/Heal reconstruction follows the same split.

CODE FAMILIES: two TPU-batchable families share this interface —
``reedsolomon`` (ops/rs.py / ops/rs_jax.py, the default) and ``cauchy``
(ops/cauchy.py: Cauchy MDS with piggybacked sub-chunks for partial
repair). The family is chosen per storage class at write time
(MINIO_TPU_EC_FAMILY*), recorded in xl.meta (ErasureInfo.algorithm),
and every decode/heal path dispatches on the STORED family, so objects
of both families coexist on the same drives. Per-family counters
(encode/decode blocks, heal/degraded ingress bytes) aggregate here for
the metrics-v3 /api/tpu group.

Backend forced with MINIO_TPU_BACKEND=numpy|jax (default: jax when any
device is available).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Iterator

import numpy as np

from ..ops import rs
from ..ops.highwayhash import hash256_batch_numpy
from . import bitrot_io, bufpool
from .bitrot_io import FAMILY_CAUCHY, FAMILY_RS

# max shards per device dispatch (HBM headroom: the hash lane arrays
# OOM above ~3072 shards of 128 KiB on a 16 GB chip)
MAX_DEVICE_SHARDS = 3072

BLOCK_SIZE = 1 << 20  # 1 MiB stripe block, reference blockSizeV2
# (/root/reference/cmd/object-api-common.go:37)


def _use_jax() -> bool:
    mode = os.environ.get("MINIO_TPU_BACKEND", "jax")
    return mode != "numpy"


def default_ec_family() -> str:
    """Write-time code family (MINIO_TPU_EC_FAMILY). Malformed values
    fall back to reedsolomon — a tuning typo must not take down PUTs —
    but reads always dispatch on the family RECORDED in xl.meta."""
    fam = os.environ.get("MINIO_TPU_EC_FAMILY", FAMILY_RS)
    return fam if fam in bitrot_io.FAMILIES else FAMILY_RS


def repair_reads_enabled() -> bool:
    """MINIO_TPU_EC_REPAIR gates the sub-chunk partial-repair read plans
    (heal + degraded GET) of sub-packetized families; decode correctness
    never depends on it — off means full-shard reads everywhere."""
    return os.environ.get("MINIO_TPU_EC_REPAIR", "1") != "0"


# -- per-family counters (metrics-v3 /api/tpu) ------------------------------

_FSTATS_LOCK = threading.Lock()
_FAMILY_STATS: dict[str, dict[str, int]] = {}
_FSTAT_KEYS = (
    "encode_blocks", "decode_blocks", "heal_ingress_bytes",
    "degraded_ingress_bytes", "repair_partial_blocks",
)


def family_stats_add(family: str, key: str, n: int = 1) -> None:
    with _FSTATS_LOCK:
        st = _FAMILY_STATS.get(family)
        if st is None:
            st = _FAMILY_STATS[family] = {k: 0 for k in _FSTAT_KEYS}
        st[key] = st.get(key, 0) + n


def family_stats_snapshot() -> dict[str, dict[str, int]]:
    """Copy of the per-family counter table; families that served no
    traffic yet report zeroed rows so metrics series exist from boot."""
    with _FSTATS_LOCK:
        out = {f: dict(st) for f, st in _FAMILY_STATS.items()}
    for fam in bitrot_io.FAMILIES:
        out.setdefault(fam, {k: 0 for k in _FSTAT_KEYS})
    return out


def decode_matrix_cache_snapshot() -> dict:
    """Per-family decode-matrix LRU hit/miss counters + entry count
    (ops/decode_cache) — the /api/tpu series that make pattern-churn
    storms diagnosable from a scrape."""
    from ..ops import decode_cache

    return decode_cache.snapshot()


def encode_blocks_numpy(
    np_codec, blocks: np.ndarray, family: str = FAMILY_RS
) -> tuple[np.ndarray, np.ndarray]:
    """CPU full-block encode+hash, byte-identical to the device rungs.

    [B, d, n] -> (shards [B, t, n], digests [B, t, 32] rs /
    [B, t, 2, 32] cauchy). Shared by ErasureCoder's no-device path and
    the dispatcher's numpy degradation rung, so the two can never
    drift."""
    from ..ops.bitrot import fast_hash256_batch

    b, d, n = blocks.shape
    t = np_codec.total_shards
    shards = np.zeros((b, t, n), dtype=np.uint8)
    shards[:, :d] = blocks
    for i in range(b):
        shards[i] = np_codec.encode(shards[i])
    if family == FAMILY_CAUCHY:
        h1 = n // 2
        # per-sub-chunk digests: two bitrot frames per shard block. The
        # halves hash as separate batches (unequal lengths when n is odd).
        d1 = fast_hash256_batch(
            np.ascontiguousarray(shards[:, :, :h1]).reshape(b * t, h1)
        )
        d2 = fast_hash256_batch(
            np.ascontiguousarray(shards[:, :, h1:]).reshape(b * t, n - h1)
        )
        digests = np.stack(
            [np.asarray(d1), np.asarray(d2)], axis=1
        ).reshape(b, t, 2, 32)
        return shards, digests
    digests = np.asarray(
        fast_hash256_batch(shards.reshape(b * t, -1))
    ).reshape(b, t, 32)
    return shards, digests


@dataclass
class EncodedPart:
    """One erasure-coded part: per-drive shard file bytes (bitrot
    interleaved) in erasure-index order [0..d+p)."""

    shard_files: list[bytes]
    size: int  # input size


class EncodedBatch:
    """One streaming-encode batch on the zero-copy plane.

    ``shard_vecs[i]`` is the writev-style buffer sequence for erasure
    index i — alternating digest-row / shard-row views into the encode
    output, framed exactly like the legacy bytearray chunks. ``raw`` is
    the input slice this batch encoded (md5/size folding); on the pooled
    path it is a memoryview into the ingest arena, so the caller MUST
    finish both the md5 fold and every ``append_file(shard_vecs[i])``
    before calling :meth:`release` — the release returns the arena to
    the pool (docs/ERASURE.md buffer-ownership contract)."""

    __slots__ = ("shard_vecs", "raw", "_lease")

    def __init__(self, shard_vecs, raw, lease=None):
        self.shard_vecs: list[list] = shard_vecs
        self.raw = raw
        self._lease = lease

    def release(self) -> None:
        """Return the backing ingest arena (if pooled). Idempotent."""
        lease, self._lease = self._lease, None
        if lease is not None:
            lease.release()


class ErasureCoder:
    def __init__(
        self, data_blocks: int, parity_blocks: int,
        block_size: int = BLOCK_SIZE, family: str = FAMILY_RS,
    ):
        self.family = bitrot_io.check_family(family)
        self.d = data_blocks
        self.p = parity_blocks
        self.t = data_blocks + parity_blocks
        self.block_size = block_size
        self.shard_size = -(-block_size // data_blocks)
        # on-disk digest overhead per shard block (1 frame for rs, 2 for
        # the sub-packetized cauchy family)
        self.frame_digests = bitrot_io.frames_per_block(self.family)
        if self.family == FAMILY_CAUCHY:
            from ..ops import cauchy as cauchy_mod

            self._np = cauchy_mod.get_codec(self.d, self.p)
        else:
            self._np = rs.get_codec(self.d, self.p)
        self._jax = None
        if _use_jax():
            if self.family == FAMILY_CAUCHY:
                from ..ops import cauchy as cauchy_mod

                self._jax = cauchy_mod.get_tpu_codec(self.d, self.p)
            else:
                from ..ops import rs_jax  # deferred: jax import is heavy

                self._jax = rs_jax.get_tpu_codec(self.d, self.p)

    @property
    def device_active(self) -> bool:
        """True when writes should route through the device dispatcher:
        either a real accelerator backend is live, or the operator
        explicitly forced MINIO_TPU_BACKEND=jax (CI exercises the device
        plane on virtual CPU devices that way). A merely-importable jax on
        a CPU-only host must NOT disable the native C++ plane."""
        if self._jax is None:
            return False
        if os.environ.get("MINIO_TPU_BACKEND") == "jax":
            return True
        import jax

        return jax.default_backend() != "cpu"

    # -- encode ------------------------------------------------------------

    def _encode_block_np(self, block: bytes) -> tuple[np.ndarray, np.ndarray]:
        from .. import native
        from ..ops.highwayhash import MINIO_KEY

        # tail blocks count like full blocks so the per-family encode
        # series stays comparable across families
        family_stats_add(self.family, "encode_blocks", 1)
        if native.available():
            shards = self._np.split(block)
            parity, digests = native.gf_encode_hash(
                self._np.parity_matrix, shards[: self.d], MINIO_KEY
            )
            shards[self.d :] = parity
            return shards, digests
        shards = self._np.encode_data(block)  # [t, per]
        digests = hash256_batch_numpy(shards)
        return shards, digests

    def _encode_full_blocks(self, blocks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """blocks: [B, d, shard_size] -> (shards [B, t, n], digests).

        digests: [B, t, 32] for reedsolomon, [B, t, 2, 32] (per
        sub-chunk) for cauchy. The device path goes through the batching
        dispatcher: blocks from concurrent requests of BOTH families
        coalesce into one stream (family tag per batch entry). The
        cauchy composite matmul needs an even shard size; odd geometries
        take the numpy path below."""
        if self._jax is not None and (
            self.family != FAMILY_CAUCHY or blocks.shape[2] % 2 == 0
        ):
            from ..parallel.dispatcher import get_dispatcher

            return get_dispatcher(self._jax, blocks.shape[2]).encode(
                blocks, codec=self._jax
            )
        family_stats_add(self.family, "encode_blocks", blocks.shape[0])
        return encode_blocks_numpy(self._np, blocks, self.family)

    def _encode_full_buffer(self, data: memoryview) -> list[bytearray]:
        """len(data) is a multiple of block_size -> per-shard file chunks
        (family-framed digest || shard interleave) for these blocks."""
        full = len(data) // self.block_size
        per = self.shard_size
        padded_block = self.d * per  # >= block_size; zero padding at tail
        bufpool.count_copy("staging")  # bytes -> numpy staging materialization
        arr = np.zeros((full, self.d, per), dtype=np.uint8)
        flat = np.frombuffer(data, dtype=np.uint8)
        if padded_block == self.block_size:
            arr[:] = flat.reshape(full, self.d, per)
        else:
            for b in range(full):
                blk = flat[b * self.block_size : (b + 1) * self.block_size]
                a = arr[b].reshape(-1)
                a[: self.block_size] = blk
        files = [bytearray() for _ in range(self.t)]
        max_blocks = max(1, MAX_DEVICE_SHARDS // self.t)
        cauchy = self.family == FAMILY_CAUCHY
        h1 = per // 2
        for start in range(0, full, max_blocks):
            chunk = arr[start : start + max_blocks]
            shards, digests = self._encode_full_blocks(chunk)
            for b in range(chunk.shape[0]):
                for i in range(self.t):
                    if cauchy:
                        files[i] += digests[b, i, 0].tobytes()
                        files[i] += shards[b, i, :h1].tobytes()
                        files[i] += digests[b, i, 1].tobytes()
                        files[i] += shards[b, i, h1:].tobytes()
                    else:
                        files[i] += digests[b, i].tobytes()
                        files[i] += shards[b, i].tobytes()
        bufpool.count_copy("frame-tobytes", full * self.t)
        return files

    def _encode_tail_buffer(self, data: bytes) -> list[bytearray]:
        """Partial final block (numpy codec, byte-identical)."""
        bufpool.count_copy("tail-block", self.t)
        if self.family == FAMILY_CAUCHY:
            shards = self._np.encode_data(data)
            family_stats_add(self.family, "encode_blocks", 1)
            return [
                bytearray(bitrot_io.frame_block(shards[i].tobytes(), self.family))
                for i in range(self.t)
            ]
        shards, digests = self._encode_block_np(data)
        files = [bytearray() for _ in range(self.t)]
        for i in range(self.t):
            files[i] += digests[i].tobytes()
            files[i] += shards[i].tobytes()
        return files

    def iter_encode(
        self, reader, max_batch_bytes: int | None = None
    ) -> "Iterator[tuple[list[bytearray], bytes]]":
        """Streaming encode: consume an iterator of byte chunks, yield
        (per-shard file chunks, the raw input slice encoded) per batch.

        Bounded memory: at most one batch of input is resident, mirroring
        the reference's block-at-a-time ring buffer
        (/root/reference/cmd/bitrot-streaming.go:108-133) at device-batch
        granularity. The raw slice lets callers fold md5/size incrementally.
        max_batch_bytes clamps the batch below the device HBM cap —
        streaming callers pass their memory bound; in-memory callers leave
        it None for full-width device dispatches.
        """
        batch_bytes = max(1, MAX_DEVICE_SHARDS // self.t) * self.block_size
        if max_batch_bytes is not None:
            batch_bytes = min(batch_bytes, max(self.block_size, max_batch_bytes))
        buf = bytearray()
        for chunk in reader:
            if not chunk:
                continue
            buf += chunk
            while len(buf) >= batch_bytes:
                bufpool.count_copy("staging")
                piece = bytes(buf[:batch_bytes])
                del buf[:batch_bytes]
                yield self._encode_full_buffer(memoryview(piece)), piece
        full = (len(buf) // self.block_size) * self.block_size
        if full:
            bufpool.count_copy("staging")
            piece = bytes(buf[:full])
            del buf[:full]
            yield self._encode_full_buffer(memoryview(piece)), piece
        if buf:
            piece = bytes(buf)
            yield self._encode_tail_buffer(piece), piece

    def _frame_into(
        self, vecs: list[list], shards: np.ndarray, digests: np.ndarray
    ) -> None:
        """Append digest/shard ROW VIEWS to the per-shard writev vectors
        — same on-disk frame interleave as _encode_full_buffer, zero
        materialization. The views pin the encode-output arrays alive
        until the disk layer consumes them."""
        cauchy = self.family == FAMILY_CAUCHY
        h1 = shards.shape[2] // 2
        for b in range(shards.shape[0]):
            for i in range(self.t):
                v = vecs[i]
                if cauchy:
                    v.append(digests[b, i, 0].data)
                    v.append(shards[b, i, :h1].data)
                    v.append(digests[b, i, 1].data)
                    v.append(shards[b, i, h1:].data)
                else:
                    v.append(digests[b, i].data)
                    v.append(shards[b, i].data)

    def _emit_zc(self, lease, nbytes: int) -> EncodedBatch:
        """Encode the first nbytes (whole stripe blocks) of a pooled
        ingest arena. The arena IS the dispatch geometry — reshape, no
        copy — and the batch takes over the lease (released by the
        caller once md5 + shard appends are done)."""
        full = nbytes // self.block_size
        arr = lease.array[:nbytes].reshape(full, self.d, self.shard_size)
        vecs: list[list] = [[] for _ in range(self.t)]
        max_blocks = max(1, MAX_DEVICE_SHARDS // self.t)
        for start in range(0, full, max_blocks):
            shards, digests = self._encode_full_blocks(arr[start : start + max_blocks])
            self._frame_into(vecs, shards, digests)
        return EncodedBatch(vecs, lease.view(nbytes), lease)

    def iter_encode_zc(
        self, reader, max_batch_bytes: int | None = None
    ) -> "Iterator[EncodedBatch]":
        """Zero-copy streaming encode: reader chunks land DIRECTLY in a
        pooled arena laid out in dispatcher geometry [B, d, shard_size],
        the device consumes the arena view, and framing yields shard-row
        views for writev-style appends — no staging copy anywhere on the
        full-block path (site "staging" stays 0; the partial tail block
        is the one inherent copy, counted as "tail-block").

        Falls back to the counting legacy path when MINIO_TPU_ZEROCOPY=0
        (the A/B lever) or when d does not divide block_size (the flat
        stream cannot alias as [B, d, per] — shard padding interleaves).
        Every yielded batch must be release()d by the caller; abandoning
        the generator releases the in-fill arena via close().
        """
        per = self.shard_size
        if self.d * per != self.block_size or not bufpool.zerocopy_enabled():
            for chunks, raw in self.iter_encode(reader, max_batch_bytes):
                yield EncodedBatch([[bytes(c)] for c in chunks], raw)
            return
        batch_blocks = max(1, MAX_DEVICE_SHARDS // self.t)
        if max_batch_bytes is not None:
            batch_blocks = max(1, min(batch_blocks, max_batch_bytes // self.block_size))
        # round DOWN to a power of two: the dispatcher buckets batch
        # sizes to powers of two, so an exact-fit arena dispatches as-is
        # (no bucket copy, no pad) instead of padding 192 -> 256
        p2 = 1
        while p2 * 2 <= batch_blocks:
            p2 <<= 1
        batch_blocks = p2
        batch_bytes = batch_blocks * self.block_size
        pool = bufpool.get_pool()
        lease = None
        mv: memoryview | None = None
        pos = 0
        try:
            for chunk in reader:
                if not chunk:
                    continue
                cmv = memoryview(chunk)
                off = 0
                while off < len(cmv):
                    if lease is None:
                        lease = pool.acquire(batch_bytes)
                        mv = lease.view(batch_bytes)
                        pos = 0
                    n = min(len(cmv) - off, batch_bytes - pos)
                    mv[pos : pos + n] = cmv[off : off + n]
                    pos += n
                    off += n
                    if pos == batch_bytes:
                        batch, lease, mv = self._emit_zc(lease, pos), None, None
                        yield batch
            if lease is not None:
                full = (pos // self.block_size) * self.block_size
                # the tail residue is copied OUT of the arena before the
                # full-block batch hands the lease to the caller
                tail = bytes(mv[full:pos]) if pos > full else b""
                if full:
                    batch, lease, mv = self._emit_zc(lease, full), None, None
                    yield batch
                else:
                    lease.release()
                    lease = mv = None
                if tail:
                    yield EncodedBatch(
                        [[bytes(c)] for c in self._encode_tail_buffer(tail)], tail
                    )
        finally:
            if lease is not None:
                lease.release()

    def encode_part(self, data: bytes) -> EncodedPart:
        """Erasure-code one in-memory part into per-drive shard files.

        Full stripe blocks go to the device in batches; the partial tail
        block (if any) uses the numpy codec. Output per drive is the
        bitrot-interleaved shard file (digest || shard block per stripe).
        Large/streamed parts should use iter_encode via the streaming
        put path instead of materializing here.
        """
        n = len(data)
        files = [bytearray() for _ in range(self.t)]
        if n == 0:
            return EncodedPart([bytes(f) for f in files], 0)
        for chunks, _raw in self.iter_encode(iter([data])):
            for i in range(self.t):
                files[i] += chunks[i]
        return EncodedPart([bytes(f) for f in files], n)

    # -- decode ------------------------------------------------------------

    def reconstruct_block(
        self, present: dict[int, np.ndarray], per_shard: int
    ) -> dict[int, np.ndarray]:
        """Rebuild ALL missing shards of one stripe block from >= d present.

        present: {erasure_index: shard bytes [per_shard]}. Returns the full
        {index: shard} map. numpy path (single block; device batching is for
        the heal plane)."""
        idxs = sorted(present.keys())
        if len(idxs) < self.d:
            raise ValueError("not enough shards to reconstruct")
        shards: list[np.ndarray | None] = [None] * self.t
        for i in idxs:
            shards[i] = present[i]
        rec = self._np.reconstruct(shards)
        family_stats_add(self.family, "decode_blocks", 1)
        return {i: rec[i] for i in range(self.t)}

    def reconstruct_data_flat(
        self,
        survivors: np.ndarray,
        present: tuple[int, ...],
        missing: tuple[int, ...],
        pool=None,
    ) -> np.ndarray:
        """Rebuild missing data shards from [d, W, per] (shard-major) input.

        Returns [len(missing), W, per]. The GET hot layout: survivors land
        contiguous per shard row, the native AVX2 GF apply consumes them
        without a transpose, and a thread pool splits the column range so
        the apply scales past one core (ctypes releases the GIL).
        """
        from .. import native

        d_, w, per = survivors.shape
        family_stats_add(self.family, "decode_blocks", w)
        if self.family == FAMILY_CAUCHY:
            # cauchy decode runs on the numpy/native GF plane: the
            # piggyback purify step chains two applies, and repair-path
            # reads (the family's point) are bandwidth- not compute-
            # bound. Device decode is a named PERF round-9 next lever.
            return self._np.reconstruct_flat(survivors, present, missing)
        if (
            self._jax is not None
            and w * self.t >= int(os.environ.get("MINIO_TPU_DECODE_MIN_SHARDS", "64"))
        ):
            from ..ops.bitrot_jax import _try_fused_decode
            from ..ops.highwayhash import MINIO_KEY

            arr = survivors.transpose(1, 0, 2)  # [W, d, per]
            # degraded GET rides the decode mega-kernel when shapes allow
            fused = _try_fused_decode(self._jax, arr, present, missing, MINIO_KEY)
            if fused is not None:
                return fused[0].transpose(1, 0, 2)
            out = self._jax.reconstruct_blocks(arr, present, missing)
            return np.asarray(out).transpose(1, 0, 2)
        mat = self._decode_rows(present, missing)
        flat = survivors.reshape(self.d, w * per)
        if native.available():
            cols = w * per
            shards_split = max(1, min(4, cols // (1 << 20)))
            if pool is not None and shards_split > 1:
                step = -(-cols // shards_split)
                out = np.empty((len(missing), cols), dtype=np.uint8)

                def apply_slice(s):
                    # the strided->contiguous copy happens in the worker too
                    return native.gf_apply(mat, flat[:, s:s + step])

                futs = [(s, pool.submit(apply_slice, s)) for s in range(0, cols, step)]
                for s, f in futs:
                    piece = f.result()
                    out[:, s:s + piece.shape[1]] = piece
            else:
                out = native.gf_apply(mat, flat)
            return out.reshape(len(missing), w, per)
        return self._np_reconstruct_batch(
            survivors.transpose(1, 0, 2), present, missing
        ).transpose(1, 0, 2)

    def _decode_rows(
        self, present: tuple[int, ...], missing: tuple[int, ...]
    ) -> np.ndarray:
        return self._np.reconstruct_rows_for(list(present), list(missing))

    def _np_reconstruct_batch(
        self,
        survivors: np.ndarray,
        present: tuple[int, ...],
        missing: tuple[int, ...],
    ) -> np.ndarray:
        from .. import native
        from ..ops import gf

        mat = self._decode_rows(present, missing)  # [m, d]
        w, _, per = survivors.shape
        if native.available():
            # AVX2 GF apply: fold the window into the column length
            flat = np.ascontiguousarray(survivors.transpose(1, 0, 2)).reshape(
                self.d, w * per
            )
            return native.gf_apply(mat, flat).reshape(len(missing), w, per).transpose(1, 0, 2)
        out = np.zeros((w, len(missing), per), dtype=np.uint8)
        for r, row in enumerate(mat):
            acc = out[:, r]
            for k in range(self.d):
                c = int(row[k])
                if c:
                    acc ^= gf.MUL_TABLE[c][survivors[:, k]]
        return out

    # -- partial repair (sub-packetized families) --------------------------

    def repair_schedule(self, missing: int):
        """Sub-chunk repair plan for ONE lost data shard, or None when
        the family has no shortcut (reedsolomon, parity loss, p < 2, or
        repair reads disabled via MINIO_TPU_EC_REPAIR=0)."""
        if self.family != FAMILY_CAUCHY or not repair_reads_enabled():
            return None
        return self._np.repair_schedule(missing)

    def repair_data_shard(self, sched, shard_size, sub2, pb_sub2, sub1):
        """Execute a repair schedule (ops/cauchy.repair_data_shard)."""
        family_stats_add(self.family, "repair_partial_blocks", 1)
        return self._np.repair_data_shard(sched, shard_size, sub2, pb_sub2, sub1)

    # -- geometry ----------------------------------------------------------

    def shard_sizes_for(self, total: int) -> list[tuple[int, int]]:
        """[(block_data_len, per_shard)] for each stripe block of a part."""
        out = []
        full = total // self.block_size
        for _ in range(full):
            out.append((self.block_size, self.shard_size))
        tail = total - full * self.block_size
        if tail:
            out.append((tail, -(-tail // self.d)))
        return out
