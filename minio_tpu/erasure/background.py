"""Background durability plane: data scanner, MRF heal queue, heal workers.

Mirrors the reference's background subsystems:
- data scanner (/root/reference/cmd/data-scanner.go): continuous namespace
  crawl with adaptive pacing; verifies objects, queues heals, feeds the
  data-usage cache.
- MRF — most-recent-failures (/root/reference/cmd/mrf.go): read-path
  degradation immediately requeues the object for heal instead of waiting
  for the next scanner cycle.
- heal workers (/root/reference/cmd/background-heal-ops.go): a bounded
  worker pool draining the heal queue.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.parse
from dataclasses import dataclass, field

from .set import TAGS_META_KEY


@dataclass
class DataUsage:
    buckets: dict[str, dict] = field(default_factory=dict)  # name -> {objects, size}
    last_update: float = 0.0
    cycles: int = 0

    def snapshot(self) -> dict:
        return {
            "bucketsCount": len(self.buckets),
            "objectsCount": sum(b["objects"] for b in self.buckets.values()),
            "objectsTotalSize": sum(b["size"] for b in self.buckets.values()),
            "lastUpdate": self.last_update,
            "cycles": self.cycles,
            "bucketsUsage": self.buckets,
        }


class MRFQueue:
    """Most-recent-failures: bounded dedup queue of objects needing heal."""

    def __init__(self, maxsize: int = 10000):
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._pending: set[tuple[str, str]] = set()
        self._mu = threading.Lock()

    def add(self, bucket: str, obj: str) -> None:
        key = (bucket, obj)
        with self._mu:
            if key in self._pending:
                return
            self._pending.add(key)
        try:
            self._q.put_nowait(key)
        except queue.Full:
            with self._mu:
                self._pending.discard(key)

    def get(self, timeout: float) -> tuple[str, str] | None:
        try:
            key = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        with self._mu:
            self._pending.discard(key)
        return key

    def __len__(self) -> int:
        return self._q.qsize()


class BackgroundOps:
    """Scanner + heal workers for one object layer (all pools/sets)."""

    def __init__(
        self,
        store,
        scan_interval: float = 60.0,
        object_sleep: float = 0.005,
        heal_workers: int = 2,
        deep_verify: bool = False,
        bucket_meta=None,
        tiers=None,
    ):
        self.store = store
        self.bucket_meta = bucket_meta  # BucketMetadataSys for ILM evaluation
        self.tiers = tiers  # TierRegistry for ILM transitions
        self.scan_interval = scan_interval
        self.object_sleep = object_sleep
        self.deep_verify = deep_verify
        self.mrf = MRFQueue()
        self.usage = DataUsage()
        self.stats = {
            "scans": 0, "objects_scanned": 0, "heals_queued": 0,
            "heals_done": 0, "heals_failed": 0,
        }
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._heal_workers = heal_workers
        # read paths report degradation here
        self.on_degraded = self.mrf.add

    # -- lifecycle ---------------------------------------------------------

    def start(self, scanner: bool = True) -> None:
        """Start the background plane. ``scanner=False`` starts only the
        MRF heal workers — SO_REUSEPORT pool workers past index 0 must
        drain their own heal-on-read queues, but duplicating the
        namespace scanner / ILM applier / fresh-disk monitor N× over the
        SAME shared drives would race transitions and multiply bg I/O
        by the pool size (cluster peers scan their OWN drives; workers
        share them)."""
        if scanner:
            t = threading.Thread(
                target=self._scan_loop, daemon=True, name="scanner"
            )
            t.start()
            self._threads.append(t)
        for i in range(self._heal_workers):
            t = threading.Thread(
                target=self._heal_loop, daemon=True, name=f"heal-{i}"
            )
            t.start()
            self._threads.append(t)
        if scanner:
            t = threading.Thread(
                target=self._disk_monitor_loop, daemon=True, name="fresh-disk"
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    # -- fresh-disk heal monitor -------------------------------------------
    # A wiped/replaced local drive is detected by its missing format.json
    # (while set peers still carry the layout) and drain-healed set-wide
    # with a resumable tracker persisted ON the healing drive. Mirrors the
    # reference's dedicated monitor + healing tracker
    # (cmd/background-newdisks-heal-ops.go:415 healFreshDisk, :559
    # monitorLocalDisksAndHeal) instead of waiting for scanner cycles.

    from ..storage.format_erasure import HEALING_TRACKER  # shared with boot heal

    def _iter_sets(self):
        for p in getattr(self.store, "pools", [self.store]):
            for s in getattr(p, "sets", [p]):
                yield s

    def _disk_monitor_loop(self) -> None:
        from ..qos.context import background_context

        interval = float(os.environ.get("MINIO_TPU_DISK_MONITOR_INTERVAL", "10"))
        if interval <= 0:
            return
        with background_context():  # drain-heal blocks ride the bg TPU lane
            while not self._stop.is_set():
                try:
                    self.check_fresh_disks()
                except Exception:  # noqa: BLE001 — monitor must never die
                    pass
                self._stop.wait(interval)

    @staticmethod
    def _drive_root(disk) -> str | None:
        lp = disk.local_path(".minio.sys", "x")
        return os.path.dirname(os.path.dirname(lp)) if lp else None

    def _unmounted_guard(self, es, disk) -> bool:
        """True when healing `disk` must be SKIPPED: its root now sits on
        the OS filesystem while healthy set peers are on real mounts — the
        signature of an unmounted drive, where a drain would fill the OS
        disk and shadow the real data on remount (reference errDriveIsRoot,
        cmd/xl-storage.go root-disk detection). Single-filesystem
        deployments (all drives on one device) heal normally."""
        root = self._drive_root(disk)
        try:
            dev = os.stat(root).st_dev
            os_dev = os.stat("/").st_dev
        except OSError:
            return True  # root path gone entirely: nothing sane to heal into
        if dev != os_dev:
            return False  # on its own mount: safe
        peer_devs = set()
        for other in es.disks:
            if other is disk or other is None:
                continue
            proot = self._drive_root(other)
            if proot is None:
                continue
            try:
                peer_devs.add(os.stat(proot).st_dev)
            except OSError:
                continue
        # all peers also on the OS device -> dev/test layout, heal away
        return bool(peer_devs) and peer_devs != {os_dev}

    def check_fresh_disks(self) -> int:
        """One monitor pass: detect + drain-heal wiped local drives.
        Returns the number of drives healed (also driven by tests/admin)."""
        healed = 0
        for es in self._iter_sets():
            for disk in es.disks:
                if disk is None or disk.local_path(".minio.sys", "x") is None:
                    continue  # remote drives are monitored by their node
                if self._unmounted_guard(es, disk):
                    continue
                try:
                    if self._fresh_disk_state(es, disk):
                        self._drain_heal(es, disk)
                        healed += 1
                        self.stats["fresh_disks_healed"] = (
                            self.stats.get("fresh_disks_healed", 0) + 1
                        )
                except Exception:  # noqa: BLE001 — retry next pass
                    pass
        return healed

    def _fresh_disk_state(self, es, disk) -> bool:
        """True when `disk` needs a set-wide drain heal: wiped (format
        gone while peers keep the layout) or carrying an interrupted
        healing tracker."""
        from ..storage import errors as serr
        from ..storage import format_erasure as fe
        from ..storage.xlstorage import SYS_DIR

        try:
            disk.read_file(SYS_DIR, fe.FORMAT_FILE)
        except (serr.FileNotFound, serr.VolumeNotFound, serr.DiskNotFound):
            # wiped at runtime: peers must still agree on the layout and
            # this drive must still know its identity (disk_id in memory)
            ref = None
            for other in es.disks:
                if other is disk or other is None:
                    continue
                try:
                    ref = fe.FormatErasure.from_json(
                        other.read_file(SYS_DIR, fe.FORMAT_FILE)
                    )
                    break
                except Exception:  # noqa: BLE001
                    continue
            my_uuid = getattr(disk, "disk_id", "")
            if ref is None or not my_uuid:
                return False
            # tracker BEFORE format: a crash in between must leave the
            # drive detectable on the next pass
            disk.create_file(
                SYS_DIR, self.HEALING_TRACKER,
                json.dumps({"started": time.time(), "buckets_done": []}).encode(),
            )
            fmt = fe.FormatErasure(id=ref.id, this=my_uuid, sets=ref.sets)
            disk.create_file(SYS_DIR, fe.FORMAT_FILE, fmt.to_json())
            return True
        # format intact: resume an interrupted drain if a tracker remains
        try:
            disk.read_file(SYS_DIR, self.HEALING_TRACKER)
            return True
        except Exception:  # noqa: BLE001
            return False

    def _drain_heal(self, es, disk) -> None:
        """Set-wide drain onto one healing drive, checkpointed by bucket.

        heal_object is idempotent per object, so replaying the in-progress
        bucket after a crash converges; completed buckets are skipped via
        the tracker (the reference's healingTracker object/byte counters
        serve the same resume purpose)."""
        from ..storage.xlstorage import SYS_DIR

        def load_tracker() -> dict:
            try:
                return json.loads(disk.read_file(SYS_DIR, self.HEALING_TRACKER))
            except Exception:  # noqa: BLE001
                return {"buckets_done": []}

        tracker = load_tracker()
        done = set(tracker.get("buckets_done", []))
        # system metadata first (bucket configs, IAM, tier config live as
        # objects under .minio.sys — the reference heals the meta bucket
        # ahead of user data in healFreshDisk)
        buckets = [".minio.sys"] + sorted(b.name for b in es.list_buckets())
        for bname in buckets:
            if self._stop.is_set():
                return  # tracker stays: next pass resumes
            if bname in done:
                continue
            try:
                disk.make_vol(bname)
            except Exception:  # noqa: BLE001 — may exist
                pass
            for obj in es.walk_objects(bname):
                if self._stop.is_set():
                    return
                try:
                    # heal EVERY version: the latest alone would leave
                    # older versions one shard short on this drive
                    versions = es.list_object_versions(bname, obj)
                    for v in versions or [None]:
                        es.heal_object(
                            bname, obj,
                            getattr(v, "version_id", "") or "",
                        )
                    self.stats["heals_done"] = self.stats.get("heals_done", 0) + 1
                except Exception:  # noqa: BLE001
                    self.stats["heals_failed"] = (
                        self.stats.get("heals_failed", 0) + 1
                    )
            done.add(bname)
            tracker["buckets_done"] = sorted(done)
            disk.create_file(
                SYS_DIR, self.HEALING_TRACKER, json.dumps(tracker).encode()
            )
        disk.delete(SYS_DIR, self.HEALING_TRACKER)

    # -- scanner -----------------------------------------------------------

    def _scan_loop(self) -> None:
        from ..qos.context import background_context

        # QoS: scanner work (ILM transitions re-encode via put, deep
        # verify heals) must never displace foreground stripe blocks in
        # the TPU batch window
        with background_context():
            while not self._stop.is_set():
                try:
                    self.scan_once()
                except Exception:  # noqa: BLE001 — scanner must never die
                    pass
                self._stop.wait(self.scan_interval)

    def scan_once(self) -> DataUsage:
        """One full namespace crawl (traced as one ``scanner`` span —
        the heal/ILM work it triggers nests under it)."""
        from .. import obs

        before = self.stats["objects_scanned"]
        with obs.span(obs.TYPE_SCANNER, "scanner.cycle") as sp:
            usage = self._scan_once_inner()
            sp.set(
                objectsScanned=self.stats["objects_scanned"] - before,
                buckets=len(usage.buckets),
            )
            return usage

    def _scan_once_inner(self) -> DataUsage:
        """One full namespace crawl: usage accounting + heal detection.

        Mirrors scanDataFolder (/root/reference/cmd/data-scanner.go:307);
        deep_verify additionally runs bitrot verification (the reference
        deep-scans each object every N cycles)."""
        from ..ilm import lifecycle as ilm

        usage: dict[str, dict] = {}
        for b in self.store.list_buckets():
            bucket_usage = {"objects": 0, "size": 0, "versions": 0}
            rules = []
            versioned = False
            if self.bucket_meta is not None:
                bm = self.bucket_meta.get(b.name)
                versioned = bm.versioning
                if bm.lifecycle:
                    try:
                        rules = ilm.parse_lifecycle(bm.lifecycle)
                    except Exception:  # noqa: BLE001 — bad config: skip ILM
                        rules = []
            for raw in self.store.walk_objects(b.name):
                if self._stop.is_set():
                    return self.usage
                self.stats["objects_scanned"] += 1
                try:
                    if rules and self._apply_lifecycle(b.name, raw, rules, versioned):
                        continue  # expired: don't account or heal
                    needs_heal = self._inspect(b.name, raw, bucket_usage)
                    if needs_heal:
                        self.mrf.add(b.name, raw)
                        self.stats["heals_queued"] += 1
                except Exception:  # noqa: BLE001 — damaged object: queue heal
                    self.mrf.add(b.name, raw)
                    self.stats["heals_queued"] += 1
                if self.object_sleep:
                    # miniovet: ignore[blocking] -- adaptive pacing analogue
                    # on the scanner daemon thread
                    time.sleep(self.object_sleep)
            usage[b.name] = bucket_usage
        self.usage.buckets = usage
        self.usage.last_update = time.time()
        self.usage.cycles += 1
        self.stats["scans"] += 1
        if self.tiers is not None:
            from ..ilm import tier as tiermod

            try:  # retry journaled warm-tier sweeps (tier GC backstop)
                tiermod.retry_journal(self.tiers)
            except Exception:  # noqa: BLE001 — next cycle retries
                pass
        return self.usage

    def _inspect(self, bucket: str, obj: str, acc: dict) -> bool:
        """Account usage; return True when the object needs healing."""
        metas, errs, sets = [], [], None
        for cand in self._candidate_sets(obj):
            metas, errs = cand._read_all_fileinfo(bucket, obj, "", False)
            if any(m is not None and m.is_valid() for m in metas):
                sets = cand
                break
        ok = [m for m in metas if m is not None and m.is_valid()]
        if not ok or sets is None:
            return False  # dangling; GC is the scanner's later job
        fi = max(ok, key=lambda m: m.mod_time)
        if fi.deleted:
            return any(e is not None for e in errs)
        acc["objects"] += 1
        acc["size"] += fi.size
        acc["versions"] += fi.num_versions or 1
        if any(e is not None for e in errs):
            return True  # missing on some drive
        if self.deep_verify:
            try:
                res = sets.heal_object(bucket, obj)
                return bool(res.get("healed"))
            except Exception:  # noqa: BLE001
                return True
        return False

    def _apply_lifecycle(
        self, bucket: str, obj: str, rules: list, versioned: bool
    ) -> bool:
        """Evaluate + apply ILM expiry for one object; True when the
        CURRENT version was expired (reference applyLifecycle in
        cmd/data-scanner.go)."""
        from ..ilm import lifecycle as ilm
        from ..storage.pathutil import decode_dir_object

        key = decode_dir_object(obj)
        versions = self.store.list_object_versions(bucket, obj)
        if not versions:
            return False
        expired_current = False
        noncurrent_rank = 0
        for i, oi in enumerate(versions):
            if not oi.is_latest:
                noncurrent_rank += 1
            st = ilm.ObjectState(
                key=key,
                mod_time_ns=oi.mod_time,
                is_latest=oi.is_latest,
                delete_marker=oi.delete_marker,
                num_versions=len(versions),
                successor_mod_time_ns=versions[i - 1].mod_time if i else 0,
                noncurrent_rank=noncurrent_rank,
                # tag-filtered rules (Filter><And><Tag>) need the stored
                # tag set; it rides the version metadata urlencoded
                tags=dict(urllib.parse.parse_qsl(
                    (oi.user_defined or {}).get(TAGS_META_KEY, ""),
                    keep_blank_values=True,
                )),
            )
            act = ilm.eval_action(rules, st)
            try:
                if act == ilm.ACTION_DELETE:
                    self.stats["ilm_expired"] = self.stats.get("ilm_expired", 0) + 1
                    self.store.delete_object(bucket, obj, versioned=versioned)
                    expired_current = not versioned
                    if not versioned:
                        self._sweep_tier(oi)  # data gone: free the warm tier
                elif act in (ilm.ACTION_DELETE_VERSION, ilm.ACTION_DELETE_MARKER):
                    self.stats["ilm_expired"] = self.stats.get("ilm_expired", 0) + 1
                    self.store.delete_object(
                        bucket, obj, version_id=oi.version_id or ""
                    )
                    if act == ilm.ACTION_DELETE_VERSION:
                        self._sweep_tier(oi)
                elif act == ilm.ACTION_TRANSITION and oi.is_latest:
                    tier_name = ilm.transition_tier_for(rules, st)
                    self._transition(bucket, obj, oi, tier_name)
            except Exception:  # noqa: BLE001 — transient; retry next cycle
                pass
        # restored copies past their window re-stub (data stays in the tier).
        # Cheap pre-check on the already-loaded version list: an extra quorum
        # metadata read per object per cycle is only paid when the marker is
        # actually present.
        from ..ilm.tier import RESTORE_EXPIRY_META

        latest = versions[0]
        if getattr(latest, "user_defined", {}).get(RESTORE_EXPIRY_META):
            try:
                self._expire_restores(bucket, obj)
            except Exception:  # noqa: BLE001
                pass
        return expired_current

    def _sweep_tier(self, oi) -> None:
        """Tier GC for an expired transitioned version (reference
        cmd/tier-sweeper.go): the stub is gone, sweep the remote data."""
        if self.tiers is None:
            return
        from ..ilm import tier as tiermod

        ud = getattr(oi, "user_defined", None) or {}
        if tiermod.is_transitioned(ud):
            tiermod.sweep_remote(self.tiers, ud)

    def _transition(self, bucket: str, obj: str, oi, tier_name: str) -> None:
        """Move one object's data to a warm tier and stub it locally
        (reference cmd/bucket-lifecycle.go:430 transition workers)."""
        from ..ilm import tier as tiermod

        if self.tiers is None:
            return
        t = self.tiers.get(tier_name)
        if t is None:
            return
        info = self.store.get_object_info(bucket, obj)
        if tiermod.is_transitioned(info.user_defined):
            return
        # compressed/SSE objects would tier their TRANSFORMED bytes and the
        # read-through could not invert them; keep those local (the
        # reference decrypts and re-encrypts per tier — future work)
        if any(k.startswith("x-minio-internal-sse") for k in info.user_defined) or \
                info.user_defined.get("x-minio-internal-compression"):
            return
        _, it = self.store.get_object(bucket, obj)
        data = b"".join(it)
        remote_key = t.remote_key(bucket, obj)
        r = t.client().put_object(t.bucket, remote_key, data)
        # any 2xx: S3 answers 200, Azure Blob answers 201 Created
        if not 200 <= r.status < 300:
            raise RuntimeError(f"tier upload failed: HTTP {r.status}")
        self.store.transition_object(bucket, obj, tier_name, remote_key)
        self.stats["ilm_transitioned"] = self.stats.get("ilm_transitioned", 0) + 1

    def _expire_restores(self, bucket: str, obj: str) -> None:
        from ..ilm import tier as tiermod

        info = self.store.get_object_info(bucket, obj)
        exp = info.user_defined.get(tiermod.RESTORE_EXPIRY_META)
        if not exp or float(exp) > time.time():
            return
        self.store.transition_object(bucket, obj, "", "", restub=True)
        self.stats["ilm_restore_expired"] = self.stats.get("ilm_restore_expired", 0) + 1

    def _candidate_sets(self, obj: str):
        """The set that would hold obj in EACH pool (multi-pool objects
        live in exactly one pool; probe like ServerPools._pool_holding)."""
        store = self.store
        for p in getattr(store, "pools", [store]):
            yield p.get_hashed_set(obj) if hasattr(p, "get_hashed_set") else p

    # -- heal workers ------------------------------------------------------

    def _heal_loop(self) -> None:
        from ..qos.context import background_context

        with background_context():  # heal blocks ride the bg TPU lane
            while not self._stop.is_set():
                item = self.mrf.get(timeout=1.0)
                if item is None:
                    continue
                bucket, obj = item
                try:
                    self.store.heal_object(bucket, obj)
                    self.stats["heals_done"] += 1
                except Exception:  # noqa: BLE001
                    self.stats["heals_failed"] += 1
