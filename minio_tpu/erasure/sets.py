"""ErasureSets — many independent erasure sets inside one pool.

Mirrors /root/reference/cmd/erasure-sets.go: objects hash to exactly one
set via SipHash-2-4 keyed by the deployment id (sipHashMod, :660); sets
never coordinate on the data path. Bucket operations broadcast to all
sets; listing merges all drives' walks (the facade exposes the same
object-layer duck type as a single ErasureSet, so listing/multipart/server
code runs unchanged on top).
"""

from __future__ import annotations

from typing import Iterator

from ..storage.datatypes import FileInfo
from ..storage.interface import StorageAPI
from ..utils.hashing import sip_hash_mod
from .quorum import BucketExists
from .set import ErasureSet
from .types import BucketInfo, ObjectInfo


class ErasureSets:
    def __init__(
        self,
        sets_disks: list[list[StorageAPI]],
        deployment_id: str,
        default_parity: int | None = None,
        pool_index: int = 0,
        ns_lock=None,
    ):
        self.deployment_id = deployment_id
        self._dep_id_bytes = _dep_bytes(deployment_id)
        self.sets = [
            ErasureSet(
                disks, default_parity, set_index=i, pool_index=pool_index,
                ns_lock=ns_lock,
            )
            for i, disks in enumerate(sets_disks)
        ]
        self.pool_index = pool_index

    # facade properties used by listing & friends
    @property
    def disks(self) -> list[StorageAPI]:
        return [d for s in self.sets for d in s.disks]

    @property
    def n(self) -> int:
        return self.sets[0].n

    @property
    def default_parity(self) -> int:
        return self.sets[0].default_parity

    def get_hashed_set(self, key: str) -> ErasureSet:
        if len(self.sets) == 1:
            return self.sets[0]
        idx = sip_hash_mod(key, len(self.sets), self._dep_id_bytes)
        return self.sets[idx]

    # -- buckets (broadcast) ----------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        errs = []
        for s in self.sets:
            try:
                s.make_bucket(bucket)
            except BucketExists as e:
                errs.append(e)
        if errs and len(errs) == len(self.sets):
            raise errs[0]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        for s in self.sets:
            # miniovet: ignore[coherence-path] -- delegates per set inside
            # the loop (self.sets is never empty); ErasureSet.delete_bucket
            # invalidates its own cache in its locked region
            s.delete_bucket(bucket, force=force)

    def bucket_exists(self, bucket: str) -> bool:
        return all(s.bucket_exists(bucket) for s in self.sets)

    def list_buckets(self) -> list[BucketInfo]:
        return self.sets[0].list_buckets()

    # -- objects (hash-routed) --------------------------------------------

    def put_object(self, bucket: str, obj: str, data: bytes, *a, **kw) -> ObjectInfo:
        return self.get_hashed_set(obj).put_object(bucket, obj, data, *a, **kw)

    def get_object(self, bucket: str, obj: str, *a, **kw):
        return self.get_hashed_set(obj).get_object(bucket, obj, *a, **kw)

    def open_object(self, bucket: str, obj: str, version_id: str = "",
                    range_hint=None):
        return self.get_hashed_set(obj).open_object(
            bucket, obj, version_id, range_hint
        )

    def get_object_info(self, bucket: str, obj: str, version_id: str = "") -> ObjectInfo:
        return self.get_hashed_set(obj).get_object_info(bucket, obj, version_id)

    def delete_object(
        self, bucket: str, obj: str, version_id: str = "", *a, **kw
    ) -> ObjectInfo:
        return self.get_hashed_set(obj).delete_object(bucket, obj, version_id, *a, **kw)

    def list_object_versions(self, bucket: str, obj: str) -> list[ObjectInfo]:
        return self.get_hashed_set(obj).list_object_versions(bucket, obj)

    def heal_object(self, bucket: str, obj: str, version_id: str = "") -> dict:
        return self.get_hashed_set(obj).heal_object(bucket, obj, version_id)

    def walk_objects(self, bucket: str, prefix: str = "") -> Iterator[str]:
        from . import listing

        for raw in listing._merged_keys(self, bucket, prefix):
            yield raw

    def set_object_tags(self, bucket, obj, tags, version_id=""):
        return self.get_hashed_set(obj).set_object_tags(bucket, obj, tags, version_id)

    def transition_object(self, bucket, obj, tier, remote_key, version_id="", restub=False):
        return self.get_hashed_set(obj).transition_object(
            bucket, obj, tier, remote_key, version_id, restub
        )

    def restore_object(self, bucket, obj, data, days, version_id=""):
        return self.get_hashed_set(obj).restore_object(
            bucket, obj, data, days, version_id
        )

    def update_object_metadata(self, bucket, obj, version_id, mutate):
        return self.get_hashed_set(obj).update_object_metadata(
            bucket, obj, version_id, mutate
        )

    def get_object_tags(self, bucket, obj, version_id=""):
        return self.get_hashed_set(obj).get_object_tags(bucket, obj, version_id)


def _dep_bytes(deployment_id: str) -> bytes:
    import uuid as _uuid

    try:
        return _uuid.UUID(deployment_id).bytes
    except ValueError:
        return (deployment_id.encode() + b"\0" * 16)[:16]
