"""Pooled stripe arenas + copy-site accounting — the zero-copy data plane.

The ingest→encode→shard-frame boundary used to move every stripe block
through 3-4 full copies (``bytes`` accumulation → numpy staging →
per-dispatch ``np.concatenate`` + pad → ``.tobytes()`` per shard for
framing), and the GET path mirrored it. On a memory-bandwidth-bound host
those copies ARE the throughput ceiling once the coding kernel runs at
memory speed (the XOR-schedule line, arXiv:2108.02692, and the
polynomial-RS evaluation, arXiv:1312.5155, both make the same point for
the kernel itself). This module provides the two primitives the
zero-copy plane is built on:

1. ``BufferPool`` — a process-wide pool of size-classed arenas with
   REFCOUNTED LEASES. ``acquire(nbytes)`` hands out a :class:`Lease`
   whose owner may write the arena; readers that outlive the owner
   (response iterators, cache fills in flight) ``retain()`` it. The
   arena returns to the free list only when the LAST holder releases —
   so a pooled buffer can never be re-leased while any reader lease is
   live: recycling is gated on the refcount reaching zero, and the
   refcount is the only door back into the pool. Violations (release of
   a dead lease / double release) are sanitizer-witnessed under
   ``MINIO_TPU_SANITIZE=1`` (event ``pool.lease-violation``) and counted
   unconditionally.

2. Copy-site accounting — ``count_copy(site, n)`` makes every REMAINING
   copy on the ingest/egress hot paths enumerable as
   ``minio_tpu_ingest_copies_total{site}``. The streaming-PUT zero-copy
   path must report ``site="staging"`` == 0 (gated in the bench ingest
   phase); boundary sites that legitimately copy (RPC serialization,
   cache-fill admission, the legacy A/B path) each carry their own named
   site, so "covered everything" is a measured claim, not an assumption.

``MINIO_TPU_ZEROCOPY=0`` keeps the previous copying paths end to end —
the A/B lever the bench phase and the byte-identity tests measure
against. Ownership rules are documented in docs/ERASURE.md
(buffer-ownership / dispatch contract) and docs/ROBUSTNESS.md (lease
rules).
"""

from __future__ import annotations

import os
import threading

import numpy as np

# size classes are powers of two from 64 KiB up; anything larger than
# the top class allocates unpooled (released back to the allocator, not
# the pool) so one giant request cannot pin the whole budget
_MIN_CLASS = 1 << 16
_MAX_CLASS = 1 << 27  # 128 MiB — one full streaming batch at the default cap

_TRUTHY = ("1", "true", "on", "yes")


def zerocopy_enabled() -> bool:
    """MINIO_TPU_ZEROCOPY gates the pooled-arena zero-copy data plane
    (streaming-PUT ingest arenas, writev shard framing, pooled GET
    gather). "0" keeps the previous copying paths — the A/B lever;
    payloads are byte-identical either way (pinned by tests)."""
    return os.environ.get("MINIO_TPU_ZEROCOPY", "1") != "0"


def _pool_budget_bytes() -> int:
    """MINIO_TPU_POOL_MB bounds idle arenas RETAINED by the pool (live
    leases are never bounded here — backpressure belongs to the request
    planes). Malformed values fall back — a tuning typo must not take
    down the data plane."""
    try:
        return int(os.environ.get("MINIO_TPU_POOL_MB", "256")) << 20
    except ValueError:
        return 256 << 20


# -- copy-site accounting ----------------------------------------------------

_COPY_LOCK = threading.Lock()
# pre-seeded: every named hot-path copy site exists from boot so the
# metrics series (and the bench gate reading them) never miss a label
_COPY_SITES = (
    "staging",          # ingest accumulation staging copy (legacy path)
    "dispatch-concat",  # dispatcher batch assembly into the bucket arena
    "dispatch-pad",     # zero-fill of the bucket pad tail
    "frame-tobytes",    # per-shard bytes materialization for framing
    "append-rpc",       # remote-drive append serialization (RPC boundary)
    "gather-join",      # GET block assembly join (legacy path)
    "cache-fill",       # cache admission snapshot (cache owns its copy)
    "tail-block",       # partial final block (numpy codec boundary)
)
_COPIES: dict[str, int] = {s: 0 for s in _COPY_SITES}


def count_copy(site: str, n: int = 1) -> None:
    """Record `n` full-buffer copies at a named hot-path site. Sites are
    the enumerable remainder of the zero-copy refactor: anything not
    counted here moves through views."""
    with _COPY_LOCK:
        _COPIES[site] = _COPIES.get(site, 0) + n


def copies_snapshot() -> dict[str, int]:
    with _COPY_LOCK:
        return dict(_COPIES)


def copies_reset() -> None:
    """Test/bench hook: zero the copy-site counters (the ingest bench
    phase asserts staging==0 over ITS window, not process lifetime)."""
    with _COPY_LOCK:
        for k in list(_COPIES):
            _COPIES[k] = 0


# -- pooled arenas -----------------------------------------------------------


class LeaseViolation(RuntimeError):
    """Release of a lease that is not live (double release / release
    after the arena returned to the pool). Raised only in tests that
    opt in; production paths report + count and carry on."""


class Lease:
    """One refcounted hold on a pooled arena.

    Ownership rule (docs/ROBUSTNESS.md): the acquirer owns the arena and
    is the only writer. Every consumer that may outlive the owner's
    scope — a response iterator serving a memoryview of the arena, a
    deferred shard append — calls ``retain()`` BEFORE the owner's
    ``release()`` can run, and ``release()`` when done. The arena is
    recyclable only at refcount zero, so a live reader lease makes
    re-lease impossible by construction.
    """

    __slots__ = ("_pool", "_arr", "_refs", "_lock", "size")

    def __init__(self, pool: "BufferPool", arr: np.ndarray):
        self._pool = pool
        self._arr: np.ndarray | None = arr
        self._refs = 1
        self._lock = threading.Lock()
        self.size = arr.nbytes

    @property
    def live(self) -> bool:
        with self._lock:
            return self._refs > 0

    @property
    def array(self) -> np.ndarray:
        """The arena as a flat uint8 array (owner-write surface)."""
        arr = self._arr
        if arr is None:
            raise LeaseViolation("arena accessed after final release")
        return arr

    def view(self, nbytes: int, offset: int = 0) -> memoryview:
        """A writable memoryview over [offset, offset+nbytes)."""
        return memoryview(self.array.data)[offset:offset + nbytes]

    def retain(self) -> "Lease":
        with self._lock:
            if self._refs <= 0:
                self._pool._violation("retain-dead")
                return self
            self._refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            if self._refs <= 0:
                self._pool._violation("double-release")
                return
            self._refs -= 1
            done = self._refs == 0
            arr, self._arr = (self._arr, None) if done else (None, self._arr)
        if done and arr is not None:
            self._pool._recycle(arr)


class BufferPool:
    """Size-classed arena pool. Thread-safe; arenas are flat uint8
    numpy arrays (the geometry — ``(blocks, d, shard_len)`` for ingest,
    assembly spans for egress — is a reshape/view, never a copy)."""

    def __init__(self, budget_bytes: int | None = None):
        self._lock = threading.Lock()
        self._free: dict[int, list[np.ndarray]] = {}
        self._budget = budget_bytes
        self.stats = {
            "acquires": 0, "hits": 0, "misses": 0, "unpooled": 0,
            "recycled_bytes": 0, "resident_bytes": 0, "live_leases": 0,
            "violations": 0,
        }

    @staticmethod
    def _class_for(nbytes: int) -> int:
        c = _MIN_CLASS
        while c < nbytes:
            c <<= 1
        return c

    def acquire(self, nbytes: int) -> Lease:
        """Lease an arena of >= nbytes. The arena's bytes are UNDEFINED
        (previous contents); owners overwrite what they use. Oversize
        requests allocate unpooled and are garbage-collected on release."""
        cls = self._class_for(nbytes)
        arr = None
        pooled = cls <= _MAX_CLASS
        with self._lock:
            self.stats["acquires"] += 1
            self.stats["live_leases"] += 1
            if pooled:
                free = self._free.get(cls)
                if free:
                    arr = free.pop()
                    self.stats["hits"] += 1
                    self.stats["resident_bytes"] -= arr.nbytes
                else:
                    self.stats["misses"] += 1
            else:
                self.stats["unpooled"] += 1
        if arr is None:
            arr = np.empty(cls if pooled else nbytes, dtype=np.uint8)
        return Lease(self, arr)

    def _recycle(self, arr: np.ndarray) -> None:
        cls = arr.nbytes
        budget = self._budget if self._budget is not None else _pool_budget_bytes()
        with self._lock:
            self.stats["live_leases"] -= 1
            self.stats["recycled_bytes"] += cls
            if (
                cls <= _MAX_CLASS
                and self._class_for(cls) == cls
                and self.stats["resident_bytes"] + cls <= budget
            ):
                self._free.setdefault(cls, []).append(arr)
                self.stats["resident_bytes"] += cls

    def _violation(self, kind: str) -> None:
        with self._lock:
            self.stats["violations"] += 1
        from ..analysis import sanitizer

        if sanitizer.enabled():
            sanitizer._report("pool.lease-violation", kind=kind)

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


_POOL: BufferPool | None = None
_POOL_LOCK = threading.Lock()


def get_pool() -> BufferPool:
    """The process-wide stripe-arena pool (ingest + egress share it; the
    size-class split keeps 1 MiB GET assemblies and 64 MiB ingest
    arenas from evicting each other — different classes, one budget)."""
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = BufferPool()
    return _POOL


def pool_stats_snapshot() -> dict:
    """Stats of the process pool (zeros before first use, so the
    metrics series exist from boot)."""
    global _POOL
    if _POOL is None:
        return {
            "acquires": 0, "hits": 0, "misses": 0, "unpooled": 0,
            "recycled_bytes": 0, "resident_bytes": 0, "live_leases": 0,
            "violations": 0,
        }
    return _POOL.stats_snapshot()
