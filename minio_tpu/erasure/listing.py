"""Object listing: merged per-drive walks with quorum resolution.

The distributed analogue in the reference streams sorted per-drive WalkDir
entries and merges/resolves them across drives
(/root/reference/cmd/metacache-set.go, metacache-entries.go). Here each
drive's sorted walk feeds a k-way merge; each candidate key resolves via
quorum metadata so dangling/partial writes don't surface. Delimiter
grouping and marker pagination mirror ListObjectsV2 semantics.
"""

from __future__ import annotations

import heapq
from typing import Iterator

from .quorum import ObjectNotFound, QuorumError, VersionNotFound
from .types import ListObjectsResult, ObjectInfo

from ..storage.pathutil import (  # noqa: F401 — re-exported API
    DIR_OBJECT_SUFFIX,
    decode_dir_object,
    encode_dir_object,
)


def _safe_walk(disk, bucket: str, base: str) -> Iterator[str]:
    """walk_dir with drive faults swallowed — the walk is a generator, so
    errors must be caught inside it, not at construction time."""
    try:
        yield from disk.walk_dir(bucket, base)
    except Exception:  # noqa: BLE001 — dead drives don't break listing
        return


def _merged_keys(es, bucket: str, prefix: str) -> Iterator[str]:
    """Sorted union of object keys across all drives under a prefix."""
    # walk from the parent of the last prefix segment so dir-marker
    # siblings ("photos/" stored as "photos__XLDIR__") are visited too
    trimmed = prefix[:-1] if prefix.endswith("/") else prefix
    base = trimmed.rsplit("/", 1)[0] if "/" in trimmed else ""
    walks = [_safe_walk(disk, bucket, base) for disk in es.disks]
    last = None
    for key in heapq.merge(*walks, key=decode_dir_object):
        if key == last:
            continue
        last = key
        dec = decode_dir_object(key)
        if dec.startswith(prefix):
            yield key
        elif not key.startswith(trimmed) and key > trimmed:
            # every encoded key that can decode into the prefix range
            # starts with `trimmed`; the sorted walk is past all of them
            return


def list_objects(
    es,
    bucket: str,
    prefix: str = "",
    marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
    include_versions: bool = False,
    version_marker: str = "",
) -> ListObjectsResult:
    """ListObjects(V1/V2/Versions) over one erasure set."""
    if not es.bucket_exists(bucket):
        from .quorum import BucketNotFound

        raise BucketNotFound(bucket)
    out = ListObjectsResult()
    seen_prefixes: set[str] = set()
    max_keys = max(0, min(max_keys, 100000))
    last_emitted = ""  # next_marker must point at the LAST RETURNED entry
    last_vid = ""

    def full() -> bool:
        return len(out.objects) + len(out.prefixes) >= max_keys

    for raw_key in _merged_keys(es, bucket, prefix):
        key = decode_dir_object(raw_key)
        if delimiter:
            rest = key[len(prefix) :]
            di = rest.find(delimiter)
            if di >= 0:
                cp = prefix + rest[: di + len(delimiter)]
                if cp in seen_prefixes or cp <= marker:
                    continue
                if full():
                    out.is_truncated = True
                    out.next_marker = last_emitted
                    return out
                seen_prefixes.add(cp)
                out.prefixes.append(cp)
                last_emitted = cp
                continue
        if include_versions:
            if key < marker:
                continue
            try:
                versions = es.list_object_versions(bucket, key)
            except (ObjectNotFound, QuorumError, VersionNotFound):
                continue
            resume_skip = key == marker and bool(version_marker)
            for oi in versions:
                if resume_skip:
                    # resume strictly after the version-id marker
                    if oi.version_id == version_marker:
                        resume_skip = False
                    continue
                if key == marker and not version_marker:
                    continue  # whole key already returned on a prior page
                oi.name = key
                if len(out.objects) >= max_keys:
                    out.is_truncated = True
                    out.next_marker = last_emitted
                    out.next_version_marker = last_vid
                    return out
                out.objects.append(oi)
                last_emitted = key
                last_vid = oi.version_id
            continue
        if key <= marker:
            continue
        try:
            oi = es.get_object_info(bucket, raw_key)
        except (ObjectNotFound, QuorumError, VersionNotFound):
            continue  # dangling or delete-marked
        if full():
            out.is_truncated = True
            out.next_marker = last_emitted
            return out
        oi.name = key
        out.objects.append(oi)
        last_emitted = key
    return out
