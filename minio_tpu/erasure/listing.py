"""Object listing: merged per-drive walks with quorum resolution.

The distributed analogue in the reference streams sorted per-drive WalkDir
entries and merges/resolves them across drives
(/root/reference/cmd/metacache-set.go, metacache-entries.go). Here each
drive's sorted walk feeds a k-way merge; each candidate key resolves via
quorum metadata so dangling/partial writes don't surface. Delimiter
grouping and marker pagination mirror ListObjectsV2 semantics.
"""

from __future__ import annotations

import hashlib
import heapq
import json
import os
import threading
import time
import weakref
from typing import Iterator

from .quorum import ErasureError, ObjectNotFound, QuorumError, VersionNotFound
from .types import ListObjectsResult, ObjectInfo
from ..storage.errors import StorageError

SYSTEM_BUCKET = ".minio.sys"

from ..storage.pathutil import (  # noqa: F401 — re-exported API
    DIR_OBJECT_SUFFIX,
    decode_dir_object,
    encode_dir_object,
)


def _safe_walk(disk, bucket: str, base: str) -> Iterator[str]:
    """walk_dir with DRIVE faults swallowed — the walk is a generator, so
    errors must be caught inside it, not at construction time. Only
    storage/transport errors are dead-drive evidence; anything else
    (a code bug in the walk) must propagate, not silently serve an
    empty listing."""
    try:
        yield from disk.walk_dir(bucket, base)
    except (StorageError, OSError):
        return


def _merged_keys(es, bucket: str, prefix: str) -> Iterator[str]:
    """Sorted union of object keys across all drives under a prefix."""
    # walk from the parent of the last prefix segment so dir-marker
    # siblings ("photos/" stored as "photos__XLDIR__") are visited too
    trimmed = prefix[:-1] if prefix.endswith("/") else prefix
    base = trimmed.rsplit("/", 1)[0] if "/" in trimmed else ""
    walks = [_safe_walk(disk, bucket, base) for disk in es.disks]
    last = None
    for key in heapq.merge(*walks, key=decode_dir_object):
        if key == last:
            continue
        last = key
        dec = decode_dir_object(key)
        if dec.startswith(prefix):
            yield key
        elif not key.startswith(trimmed) and key > trimmed:
            # every encoded key that can decode into the prefix range
            # starts with `trimmed`; the sorted walk is past all of them
            return


# ---- listing metacache -----------------------------------------------------
# Two jobs: continuation pages resume a cached key stream instead of
# re-walking every drive per page (the reference caches listing streams
# as objects under .minio.sys and resumes them by continuation token,
# /root/reference/cmd/metacache-set.go:319, metacache-server-pool.go:60),
# and REPEATED first-page scans of the same (bucket, prefix) — training
# manifests, dashboards — reuse the previous walk outright. Coherence:
# every object mutation invalidates its bucket's entries through the
# cache choke point (cache/core.SetCache.invalidate_object), so a
# same-node put -> list round-trip always sees the new key; cross-node
# the TTL plus the coherence broadcast bound staleness.

_MC_LOCK = threading.Lock()
# (store-id, bucket, prefix) -> (created, keys | None, store-weakref);
# keys=None is the memoized "too big to cache" verdict so huge prefixes
# don't double-walk. The weakref guards against CPython id() reuse after
# a store is garbage-collected.
_MC_MEM: dict[tuple[int, str, str], tuple[float, list[str] | None, object]] = {}
_MC_MAX_ENTRIES = 256
_MC_STATS = {"hits": 0, "misses": 0, "invalidations": 0, "stores": 0}
# per-bucket invalidation sequence: a first-page walk captured across a
# concurrent mutation must not be memoized (the walk may predate the new
# key but would be stamped fresh) — snapshot at walk start, compare at
# store time
_MC_SEQ = 0
_MC_BSEQ: dict[str, int] = {}


def _mc_bucket_seq(bucket: str) -> int:
    with _MC_LOCK:
        return _MC_BSEQ.get(bucket, 0)


def _mc_ttl() -> float:
    return float(os.environ.get("MINIO_TPU_METACACHE_TTL", "15"))


def _mc_max_keys() -> int:
    return int(os.environ.get("MINIO_TPU_METACACHE_MAX_KEYS", "200000"))


def invalidate_bucket(bucket: str) -> None:
    """Drop in-memory cache entries for a bucket (choke-point API: called
    on every object mutation in it, and on bucket delete/recreate)."""
    global _MC_SEQ
    with _MC_LOCK:
        _MC_SEQ += 1
        _MC_BSEQ[bucket] = _MC_SEQ
        if len(_MC_BSEQ) > 4096:
            _MC_BSEQ.clear()  # seqs are global-monotonic: a forgotten
            # bucket re-registers at a HIGHER seq on its next mutation,
            # and _mc_bucket_seq falling back to 0 only rejects stores
        victims = [k for k in _MC_MEM if k[1] == bucket]
        for ck in victims:
            del _MC_MEM[ck]
        _MC_STATS["invalidations"] += len(victims)


def clear_metacache() -> int:
    """Admin cache/clear: drop every in-memory listing entry."""
    with _MC_LOCK:
        n = len(_MC_MEM)
        _MC_MEM.clear()
    return n


def metacache_stats() -> dict:
    with _MC_LOCK:
        return dict(_MC_STATS, entries=len(_MC_MEM))


def _mc_mem_lookup(es, bucket: str, prefix: str) -> list[str] | None:
    """Fresh in-memory key list for (bucket, prefix), else None. Unlike
    ``_metacache_keys`` this never reads the persisted copy or builds —
    it is the zero-I/O fast path for repeated first-page scans."""
    from ..cache import core as cache_core

    ttl = _mc_ttl()
    if ttl <= 0 or bucket.startswith(SYSTEM_BUCKET) or not cache_core.enabled():
        return None
    now = time.time()
    ck = (id(es), bucket, prefix)
    with _MC_LOCK:
        hit = _MC_MEM.get(ck)
        if hit and hit[1] is not None and now - hit[0] < ttl and hit[2]() is es:
            _MC_STATS["hits"] += 1
            return hit[1]
    return None


def _mc_mem_store(es, bucket: str, prefix: str, keys: list[str],
                  seq0: int) -> None:
    """Memoize a fully-consumed walk so the NEXT scan of this prefix is
    zero-I/O (in-memory only; the persisted tier stays owned by the
    pagination builder in ``_metacache_keys``). ``seq0`` is the bucket's
    invalidation sequence at WALK START: a mutation that landed mid-walk
    rejects the store — the walk may predate the new key, and memoizing
    it with a fresh timestamp would hide the key for a whole TTL."""
    from ..cache import core as cache_core

    ttl = _mc_ttl()
    if ttl <= 0 or bucket.startswith(SYSTEM_BUCKET) or not cache_core.enabled():
        return
    if len(keys) > _mc_max_keys():
        return
    now = time.time()
    with _MC_LOCK:
        if _MC_BSEQ.get(bucket, 0) != seq0:
            return  # invalidated while walking: not trustworthy
        _mc_evict(now, ttl)
        _MC_MEM[(id(es), bucket, prefix)] = (now, list(keys), weakref.ref(es))
        _MC_STATS["stores"] += 1


def _mc_evict(now: float, ttl: float) -> None:
    """Caller holds _MC_LOCK: drop expired entries + cap total count."""
    for ck in [k for k, entry in _MC_MEM.items() if now - entry[0] >= ttl]:
        del _MC_MEM[ck]
    while len(_MC_MEM) > _MC_MAX_ENTRIES:
        _MC_MEM.pop(next(iter(_MC_MEM)))


def _metacache_keys(es, bucket: str, prefix: str) -> list[str] | None:
    """Sorted raw keys for (bucket, prefix) from the metacache, building
    and persisting it on first paginated access. None = stream the walk
    (cache disabled, stale path, or namespace too big to cache)."""
    ttl = _mc_ttl()
    if ttl <= 0 or bucket.startswith(SYSTEM_BUCKET):
        return None
    now = time.time()
    ck = (id(es), bucket, prefix)  # store identity: two stores in one
    # process (e.g. in-process site pairs) must never share key lists
    with _MC_LOCK:
        _mc_evict(now, ttl)
        hit = _MC_MEM.get(ck)
    if hit and now - hit[0] < ttl and hit[2]() is es:
        with _MC_LOCK:
            _MC_STATS["hits"] += 1
        return hit[1]
    with _MC_LOCK:
        _MC_STATS["misses"] += 1
    obj_key = (
        f"buckets/{bucket}/.metacache/"
        f"{hashlib.sha1(prefix.encode()).hexdigest()}.json"
    )
    # another node of the cluster may have persisted this listing already
    try:
        _, it = es.get_object(SYSTEM_BUCKET, obj_key)
        doc = json.loads(b"".join(it))
        if now - float(doc.get("created", 0)) < ttl:
            keys = list(doc.get("keys", []))
            with _MC_LOCK:
                _MC_MEM[ck] = (float(doc["created"]), keys, weakref.ref(es))
            return keys
        # expired persisted cache: reclaim the space opportunistically
        try:
            es.delete_object(SYSTEM_BUCKET, obj_key)
        except (ErasureError, StorageError, OSError):
            pass  # reclaim is best-effort; the TTL already expired it
    # miniovet: ignore[error-taint] -- any failure here (absent object,
    # corrupt doc, quorum loss) is recoverable by design: the walk below
    # rebuilds the listing from the drives, which is the source of truth
    except Exception:  # noqa: BLE001 — absent/corrupt: rebuild
        pass
    keys: list[str] | None = []
    cap = _mc_max_keys()
    for raw in _merged_keys(es, bucket, prefix):
        keys.append(raw)
        if len(keys) > cap:
            keys = None  # memoize the verdict: pages stream the walk
            break
    with _MC_LOCK:
        _MC_MEM[ck] = (now, keys, weakref.ref(es))
    if keys is not None:
        try:
            es.put_object(
                SYSTEM_BUCKET, obj_key,
                json.dumps({"created": now, "keys": keys}).encode(),
            )
        except (ErasureError, StorageError, OSError):
            pass  # persistence is an optimization; memory cache serves
    return keys


def list_objects(
    es,
    bucket: str,
    prefix: str = "",
    marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
    include_versions: bool = False,
    version_marker: str = "",
) -> ListObjectsResult:
    """ListObjects(V1/V2/Versions) over one erasure set."""
    if not es.bucket_exists(bucket):
        from .quorum import BucketNotFound

        raise BucketNotFound(bucket)
    out = ListObjectsResult()
    seen_prefixes: set[str] = set()
    max_keys = max(0, min(max_keys, 100000))
    last_emitted = ""  # next_marker must point at the LAST RETURNED entry
    last_vid = ""

    def full() -> bool:
        return len(out.objects) + len(out.prefixes) >= max_keys

    key_source: Iterator[str] | list[str] | None = None
    capture: list[str] | None = None
    if marker:
        # continuation page: reuse (or build once) the cached key stream
        # instead of re-walking every drive per page
        key_source = _metacache_keys(es, bucket, prefix)
    else:
        # repeated first-page scan: a fresh prior walk serves in-memory
        key_source = _mc_mem_lookup(es, bucket, prefix)
    cap_seq0 = 0
    if key_source is None:
        key_source = _merged_keys(es, bucket, prefix)
        if not marker:
            # capture the walk; if this page consumes it COMPLETELY (no
            # truncation) the keys are the full prefix listing — cache
            # them for free so the next scan is zero-I/O
            capture = []
            cap_seq0 = _mc_bucket_seq(bucket)

    cap_max = _mc_max_keys()
    for raw_key in key_source:
        if capture is not None:
            capture.append(raw_key)
            if len(capture) > cap_max:
                capture = None
        key = decode_dir_object(raw_key)
        if delimiter:
            rest = key[len(prefix) :]
            di = rest.find(delimiter)
            if di >= 0:
                cp = prefix + rest[: di + len(delimiter)]
                if cp in seen_prefixes or cp <= marker:
                    continue
                if full():
                    out.is_truncated = True
                    out.next_marker = last_emitted
                    return out
                seen_prefixes.add(cp)
                out.prefixes.append(cp)
                last_emitted = cp
                continue
        if include_versions:
            if key < marker:
                continue
            try:
                versions = es.list_object_versions(bucket, key)
            except (ObjectNotFound, QuorumError, VersionNotFound):
                continue
            resume_skip = key == marker and bool(version_marker)
            for oi in versions:
                if resume_skip:
                    # resume strictly after the version-id marker
                    if oi.version_id == version_marker:
                        resume_skip = False
                    continue
                if key == marker and not version_marker:
                    continue  # whole key already returned on a prior page
                oi.name = key
                if len(out.objects) >= max_keys:
                    out.is_truncated = True
                    out.next_marker = last_emitted
                    out.next_version_marker = last_vid
                    return out
                out.objects.append(oi)
                last_emitted = key
                last_vid = oi.version_id
            continue
        if key <= marker:
            continue
        try:
            oi = es.get_object_info(bucket, raw_key)
        except (ObjectNotFound, QuorumError, VersionNotFound):
            continue  # dangling or delete-marked
        if full():
            out.is_truncated = True
            out.next_marker = last_emitted
            return out
        oi.name = key
        out.objects.append(oi)
        last_emitted = key
    if capture is not None:
        _mc_mem_store(es, bucket, prefix, capture, cap_seq0)
    return out
