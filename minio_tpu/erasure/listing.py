"""Object listing: merged per-drive walks with quorum resolution.

The distributed analogue in the reference streams sorted per-drive WalkDir
entries and merges/resolves them across drives
(/root/reference/cmd/metacache-set.go, metacache-entries.go). Here each
drive's sorted walk feeds a k-way merge; each candidate key resolves via
quorum metadata so dangling/partial writes don't surface. Delimiter
grouping and marker pagination mirror ListObjectsV2 semantics.
"""

from __future__ import annotations

import bisect
import hashlib
import heapq
import json
import os
import threading
import time
import weakref
from typing import Callable, Iterator

from .quorum import ErasureError, ObjectNotFound, QuorumError, VersionNotFound
from .types import ListObjectsResult, ObjectInfo
from ..storage.errors import StorageError

SYSTEM_BUCKET = ".minio.sys"

from ..storage.pathutil import (  # noqa: F401 — re-exported API
    DIR_OBJECT_SUFFIX,
    decode_dir_object,
    encode_dir_object,
)


def _safe_walk(disk, bucket: str, base: str) -> Iterator[str]:
    """walk_dir with DRIVE faults swallowed — the walk is a generator, so
    errors must be caught inside it, not at construction time. Only
    storage/transport errors are dead-drive evidence; anything else
    (a code bug in the walk) must propagate, not silently serve an
    empty listing."""
    try:
        yield from disk.walk_dir(bucket, base)
    except (StorageError, OSError):
        return


def _merged_keys(es, bucket: str, prefix: str) -> Iterator[str]:
    """Sorted union of object keys across all drives under a prefix."""
    with _MC_LOCK:
        _MC_STATS["walks"] += 1
    # walk from the parent of the last prefix segment so dir-marker
    # siblings ("photos/" stored as "photos__XLDIR__") are visited too
    trimmed = prefix[:-1] if prefix.endswith("/") else prefix
    base = trimmed.rsplit("/", 1)[0] if "/" in trimmed else ""
    walks = [_safe_walk(disk, bucket, base) for disk in es.disks]
    last = None
    for key in heapq.merge(*walks, key=decode_dir_object):
        if key == last:
            continue
        last = key
        dec = decode_dir_object(key)
        if dec.startswith(prefix):
            yield key
        elif not key.startswith(trimmed) and key > trimmed:
            # every encoded key that can decode into the prefix range
            # starts with `trimmed`; the sorted walk is past all of them
            return


# ---- listing metacache -----------------------------------------------------
# Two jobs: continuation pages resume a cached key stream instead of
# re-walking every drive per page (the reference caches listing streams
# as objects under .minio.sys and resumes them by continuation token,
# /root/reference/cmd/metacache-set.go:319, metacache-server-pool.go:60),
# and REPEATED first-page scans of the same (bucket, prefix) — training
# manifests, dashboards — reuse the previous walk outright.
#
# The key stream is SHARDED by key range (ShardedKeys): the sorted walk
# splits into ~MINIO_TPU_METACACHE_SHARD_KEYS-entry shards with a small
# decoded-boundary index, so resuming a continuation token is a bisect
# into one shard (O(log shards + page) per page) instead of an O(total
# keys) scan — at 10^6 keys that is the difference between flat and
# linear page latency. Shards persist individually under .minio.sys
# (index doc + one doc per shard), so a restarted node or a cluster
# peer adopts the index and faults in only the shards its pages touch.
#
# Coherence: every object mutation invalidates its bucket's entries
# through the cache choke point (cache/core.SetCache.invalidate_object),
# so a same-node put -> list round-trip always sees the new key. The
# persisted index is stamped with the bucket's invalidation sequence at
# walk start; an adopter accepts it only while its own in-memory
# sequence still matches (or is 0 — fresh boot, where the TTL alone
# bounds staleness, same trust as a cross-node adoption).

_MC_LOCK = threading.Lock()
# (store-id, bucket, prefix) -> (created, ShardedKeys | None, store-weakref);
# None is the memoized "too big to cache" verdict so huge prefixes
# don't double-walk. The weakref guards against CPython id() reuse after
# a store is garbage-collected.
_MC_MEM: dict[tuple[int, str, str], tuple[float, object, object]] = {}
_MC_MAX_ENTRIES = 256
_MC_STATS = {
    "hits": 0,
    "misses": 0,
    "invalidations": 0,
    "stores": 0,
    "evictions": 0,
    "walks": 0,           # full merged drive walks started
    "persisted": 0,       # shard + index docs written to .minio.sys
    "persist_adopts": 0,  # persisted indexes adopted (restart / peer)
    "shard_loads": 0,     # individual shard docs faulted in on demand
    "build_waits": 0,     # misses that waited on a sibling's build
}
# build singleflight: concurrent paginated misses on one (store, bucket,
# prefix) would each walk every drive — at 10^5+ keys that thundering
# herd is minutes of redundant I/O. The first miss claims the key and
# walks; the rest wait on its event, then re-check the memory cache.
_MC_BUILDING: dict[tuple[int, str, str], threading.Event] = {}
# per-bucket invalidation sequence: a walk captured across a concurrent
# mutation must not be memoized (the walk may predate the new key but
# would be stamped fresh) — snapshot at walk start, compare at store time
_MC_SEQ = 0
_MC_BSEQ: dict[str, int] = {}


class MetacacheGone(Exception):
    """A lazily-persisted shard could not be faulted in (deleted,
    corrupt, or torn overwrite): the cached stream is unusable and the
    caller must fall back to a fresh drive walk."""


class ShardedKeys:
    """Key-range-sharded sorted key stream for one (bucket, prefix).

    ``shards`` holds ENCODED keys (sorted by decoded form, exactly as
    the merged walk yields them); ``bounds`` holds the DECODED first key
    of each shard so a continuation marker bisects straight to its
    shard. A shard slot may be None when the object was adopted from
    the persisted tier — ``loader(i)`` faults it in on first touch."""

    __slots__ = ("shards", "bounds", "total", "_loader", "_lock")

    def __init__(
        self,
        shards: list[list[str] | None],
        bounds: list[str],
        total: int,
        loader: Callable[[int], list[str]] | None = None,
    ):
        self.shards = shards
        self.bounds = bounds
        self.total = total
        self._loader = loader
        self._lock = threading.Lock()

    @staticmethod
    def build(keys: list[str], shard_keys: int) -> "ShardedKeys":
        n = max(1, shard_keys)
        shards: list[list[str] | None] = [
            keys[i : i + n] for i in range(0, len(keys), n)
        ]
        bounds = [decode_dir_object(s[0]) for s in shards]
        return ShardedKeys(shards, bounds, len(keys))

    def loaded_shards(self) -> int:
        return sum(1 for s in self.shards if s is not None)

    def _shard(self, i: int) -> list[str]:
        s = self.shards[i]
        if s is None:
            with self._lock:
                s = self.shards[i]
                if s is None:
                    if self._loader is None:
                        raise MetacacheGone(f"shard {i} missing")
                    s = self._loader(i)
                    self.shards[i] = s
        return s

    def iter_from(self, marker: str = "") -> Iterator[str]:
        """Yield encoded keys whose DECODED form is >= marker (versions
        pagination resumes ON the marker key). O(log shards) to find the
        resume point; only shards at/after it are touched."""
        if not self.shards:
            return
        si = 0
        if marker:
            si = max(bisect.bisect_right(self.bounds, marker) - 1, 0)
        first = self._shard(si)
        start = (
            bisect.bisect_left(first, marker, key=decode_dir_object)
            if marker
            else 0
        )
        yield from first[start:]
        for i in range(si + 1, len(self.shards)):
            yield from self._shard(i)

    def __iter__(self) -> Iterator[str]:
        return self.iter_from("")


def _mc_bucket_seq(bucket: str) -> int:
    with _MC_LOCK:
        return _MC_BSEQ.get(bucket, 0)


def _mc_ttl() -> float:
    return float(os.environ.get("MINIO_TPU_METACACHE_TTL", "15"))


def _mc_max_keys() -> int:
    return int(os.environ.get("MINIO_TPU_METACACHE_MAX_KEYS", "200000"))


def _mc_shard_keys() -> int:
    return int(os.environ.get("MINIO_TPU_METACACHE_SHARD_KEYS", "8192"))


def _mc_persist_enabled() -> bool:
    return os.environ.get("MINIO_TPU_METACACHE_PERSIST", "1") != "0"


def invalidate_bucket(bucket: str) -> None:
    """Drop in-memory cache entries for a bucket (choke-point API: called
    on every object mutation in it, and on bucket delete/recreate)."""
    global _MC_SEQ
    with _MC_LOCK:
        _MC_SEQ += 1
        _MC_BSEQ[bucket] = _MC_SEQ
        if len(_MC_BSEQ) > 4096:
            _MC_BSEQ.clear()  # seqs are global-monotonic: a forgotten
            # bucket re-registers at a HIGHER seq on its next mutation,
            # and _mc_bucket_seq falling back to 0 only rejects stores
        victims = [k for k in _MC_MEM if k[1] == bucket]
        for ck in victims:
            del _MC_MEM[ck]
        _MC_STATS["invalidations"] += len(victims)


def clear_metacache() -> int:
    """Admin cache/clear: drop every in-memory listing entry."""
    with _MC_LOCK:
        n = len(_MC_MEM)
        _MC_MEM.clear()
    return n


def metacache_stats() -> dict:
    with _MC_LOCK:
        shards = sum(
            entry[1].loaded_shards()
            for entry in _MC_MEM.values()
            if isinstance(entry[1], ShardedKeys)
        )
        return dict(_MC_STATS, entries=len(_MC_MEM), shards=shards)


def _mc_mem_lookup(es, bucket: str, prefix: str) -> "ShardedKeys | None":
    """Fresh in-memory key stream for (bucket, prefix), else None. Unlike
    ``_metacache_keys`` this never reads the persisted index or builds —
    it is the zero-walk fast path for repeated first-page scans (an
    adopted entry may still fault individual shards in from the
    persisted tier on first touch)."""
    from ..cache import core as cache_core

    ttl = _mc_ttl()
    if ttl <= 0 or bucket.startswith(SYSTEM_BUCKET) or not cache_core.enabled():
        return None
    now = time.time()
    ck = (id(es), bucket, prefix)
    with _MC_LOCK:
        hit = _MC_MEM.get(ck)
        if hit and hit[1] is not None and now - hit[0] < ttl and hit[2]() is es:
            _MC_STATS["hits"] += 1
            return hit[1]
    return None


def _mc_mem_store(es, bucket: str, prefix: str, keys: list[str],
                  seq0: int) -> None:
    """Memoize a fully-consumed walk so the NEXT scan of this prefix is
    zero-walk (in-memory only; the persisted tier stays owned by the
    pagination builder in ``_metacache_keys``). ``seq0`` is the bucket's
    invalidation sequence at WALK START: a mutation that landed mid-walk
    rejects the store — the walk may predate the new key, and memoizing
    it with a fresh timestamp would hide the key for a whole TTL."""
    from ..cache import core as cache_core

    ttl = _mc_ttl()
    if ttl <= 0 or bucket.startswith(SYSTEM_BUCKET) or not cache_core.enabled():
        return
    if len(keys) > _mc_max_keys():
        return
    now = time.time()
    sk = ShardedKeys.build(list(keys), _mc_shard_keys())
    with _MC_LOCK:
        if _MC_BSEQ.get(bucket, 0) != seq0:
            return  # invalidated while walking: not trustworthy
        _mc_evict(now, ttl)
        _MC_MEM[(id(es), bucket, prefix)] = (now, sk, weakref.ref(es))
        _MC_STATS["stores"] += 1


def _mc_evict(now: float, ttl: float) -> None:
    """Caller holds _MC_LOCK: drop expired entries + cap total count."""
    victims = [k for k, entry in _MC_MEM.items() if now - entry[0] >= ttl]
    for ck in victims:
        del _MC_MEM[ck]
    _MC_STATS["evictions"] += len(victims)
    while len(_MC_MEM) > _MC_MAX_ENTRIES:
        _MC_MEM.pop(next(iter(_MC_MEM)))
        _MC_STATS["evictions"] += 1


def _mc_drop(es, bucket: str, prefix: str) -> None:
    """Drop one unusable entry (failed shard fault-in)."""
    with _MC_LOCK:
        _MC_MEM.pop((id(es), bucket, prefix), None)
        _MC_STATS["evictions"] += 1


def _mc_doc_base(bucket: str, prefix: str) -> str:
    h = hashlib.sha1(prefix.encode()).hexdigest()
    return f"buckets/{bucket}/.metacache/{h}"


def _mc_persist(es, bucket: str, prefix: str, sk: ShardedKeys,
                created: float, seq0: int) -> None:
    """Write the shard docs then the index (index last: an adopter never
    sees an index whose shards aren't durable yet; each shard doc echoes
    the index's created stamp so a torn overwrite is detected at
    fault-in time and falls back to a walk)."""
    if not _mc_persist_enabled():
        return
    base = _mc_doc_base(bucket, prefix)
    try:
        for i, s in enumerate(sk.shards):
            es.put_object(
                SYSTEM_BUCKET, f"{base}.s{i:05d}.json",
                json.dumps({"created": created, "keys": s}).encode(),
            )
        es.put_object(
            SYSTEM_BUCKET, f"{base}.idx.json",
            json.dumps({
                "created": created,
                "seq": seq0,
                "counts": [len(s) for s in sk.shards],
                "bounds": sk.bounds,
            }).encode(),
        )
    except (ErasureError, StorageError, OSError):
        return  # persistence is an optimization; memory cache serves
    with _MC_LOCK:
        _MC_STATS["persisted"] += len(sk.shards) + 1


def _mc_persist_adopt(
    es, bucket: str, prefix: str, now: float, ttl: float, bseq: int
) -> tuple[float, ShardedKeys] | None:
    """Adopt a persisted index (another node, or this node before a
    restart, built it): shards stay unloaded until a page touches them.
    Accepted only while TTL-fresh AND the stamped invalidation sequence
    matches this node's — bseq 0 means no mutation seen since boot, so
    the TTL alone bounds staleness (cross-node trust)."""
    if not _mc_persist_enabled():
        return None
    base = _mc_doc_base(bucket, prefix)
    try:
        _, it = es.get_object(SYSTEM_BUCKET, f"{base}.idx.json")
        doc = json.loads(b"".join(it))
        created = float(doc["created"])
        counts = [int(c) for c in doc["counts"]]
        bounds = [str(b) for b in doc["bounds"]]
        seq = int(doc.get("seq", -1))
    # miniovet: ignore[error-taint] -- any failure here (absent index,
    # corrupt doc, quorum loss) is recoverable by design: the caller
    # rebuilds from the drives, which is the source of truth
    except Exception:  # noqa: BLE001 — absent/corrupt: rebuild
        return None
    if now - created >= ttl:
        # expired persisted cache: reclaim the space opportunistically
        try:
            for i in range(len(counts)):
                es.delete_object(SYSTEM_BUCKET, f"{base}.s{i:05d}.json")
            es.delete_object(SYSTEM_BUCKET, f"{base}.idx.json")
        except (ErasureError, StorageError, OSError):
            pass  # reclaim is best-effort; the TTL already expired it
        return None
    if bseq not in (0, seq):
        return None  # a local mutation outran this index: stale
    if len(bounds) != len(counts) or sum(counts) > _mc_max_keys():
        return None

    def load(i: int) -> list[str]:
        try:
            _, sit = es.get_object(SYSTEM_BUCKET, f"{base}.s{i:05d}.json")
            sdoc = json.loads(b"".join(sit))
            if float(sdoc["created"]) != created:
                raise MetacacheGone(f"shard {i} from a different build")
            keys = [str(k) for k in sdoc["keys"]]
            if len(keys) != counts[i]:
                raise MetacacheGone(f"shard {i} truncated")
        except MetacacheGone:
            raise
        # a missing/corrupt shard doc is recoverable by design:
        # MetacacheGone makes the lister fall back to a fresh drive walk
        except Exception as e:  # noqa: BLE001 — absent/corrupt: rewalk
            raise MetacacheGone(f"shard {i}: {e}") from None
        with _MC_LOCK:
            _MC_STATS["shard_loads"] += 1
        return keys

    with _MC_LOCK:
        _MC_STATS["persist_adopts"] += 1
    return created, ShardedKeys(
        [None] * len(counts), bounds, sum(counts), loader=load
    )


def _metacache_keys(es, bucket: str, prefix: str) -> "ShardedKeys | None":
    """Sharded key stream for (bucket, prefix) from the metacache,
    building and persisting it on first paginated access. None = stream
    the walk (cache disabled, stale path, or namespace too big to
    cache)."""
    ttl = _mc_ttl()
    if ttl <= 0 or bucket.startswith(SYSTEM_BUCKET):
        return None
    now = time.time()
    ck = (id(es), bucket, prefix)  # store identity: two stores in one
    # process (e.g. in-process site pairs) must never share key lists
    with _MC_LOCK:
        _mc_evict(now, ttl)
        hit = _MC_MEM.get(ck)
    if hit and now - hit[0] < ttl and hit[2]() is es:
        with _MC_LOCK:
            _MC_STATS["hits"] += 1
        return hit[1]
    with _MC_LOCK:
        _MC_STATS["misses"] += 1
    # singleflight the build: if a sibling request is already walking
    # this (store, bucket, prefix), wait for its verdict and re-check
    # the memory cache instead of starting a redundant full walk
    while True:
        with _MC_LOCK:
            ev = _MC_BUILDING.get(ck)
            if ev is None:
                _MC_BUILDING[ck] = threading.Event()
                break
            _MC_STATS["build_waits"] += 1
        ev.wait()
        now = time.time()
        with _MC_LOCK:
            hit = _MC_MEM.get(ck)
        if hit and now - hit[0] < ttl and hit[2]() is es:
            with _MC_LOCK:
                _MC_STATS["hits"] += 1
            return hit[1]
        # builder's store was rejected (mutation mid-walk) or expired:
        # loop to claim the build slot ourselves
    try:
        seq0 = _mc_bucket_seq(bucket)
        # another node of the cluster (or this node before a restart)
        # may have persisted this listing already — adopt its index,
        # fault shards in per page
        adopted = _mc_persist_adopt(es, bucket, prefix, now, ttl, seq0)
        if adopted is not None:
            created, sk = adopted
            with _MC_LOCK:
                _MC_MEM[ck] = (created, sk, weakref.ref(es))
            return sk
        keys: list[str] | None = []
        cap = _mc_max_keys()
        for raw in _merged_keys(es, bucket, prefix):
            keys.append(raw)
            if len(keys) > cap:
                keys = None  # memoize the verdict: pages stream the walk
                break
        if keys is None:
            with _MC_LOCK:
                _MC_MEM[ck] = (now, None, weakref.ref(es))
            return None
        sk = ShardedKeys.build(keys, _mc_shard_keys())
        # stamp at build END, not walk start: the seq check below proves
        # no mutation landed during the walk, so the key list equals the
        # listing as of NOW — and a walk that itself takes a sizable
        # fraction of the TTL (10^5+ keys on a loaded box) must not be
        # born half-expired
        done = time.time()
        with _MC_LOCK:
            if _MC_BSEQ.get(bucket, 0) != seq0:
                # a mutation landed mid-walk: serve THIS page from the
                # walk we just did (point-in-time listing) but neither
                # memoize nor persist it — stamping it fresh would hide
                # the new key for a whole TTL (PR 5's first-page rule,
                # applied to the pagination builder)
                return sk
            _MC_MEM[ck] = (done, sk, weakref.ref(es))
            _MC_STATS["stores"] += 1
        _mc_persist(es, bucket, prefix, sk, done, seq0)
        return sk
    finally:
        with _MC_LOCK:
            ev = _MC_BUILDING.pop(ck, None)
        if ev is not None:
            ev.set()


def list_objects(
    es,
    bucket: str,
    prefix: str = "",
    marker: str = "",
    delimiter: str = "",
    max_keys: int = 1000,
    include_versions: bool = False,
    version_marker: str = "",
) -> ListObjectsResult:
    """ListObjects(V1/V2/Versions) over one erasure set."""
    if not es.bucket_exists(bucket):
        from .quorum import BucketNotFound

        raise BucketNotFound(bucket)
    max_keys = max(0, min(max_keys, 100000))

    def _run(key_source: Iterator[str], capture: list[str] | None,
             cap_seq0: int) -> ListObjectsResult:
        out = ListObjectsResult()
        seen_prefixes: set[str] = set()
        last_emitted = ""  # next_marker points at the LAST RETURNED entry
        last_vid = ""

        def full() -> bool:
            return len(out.objects) + len(out.prefixes) >= max_keys

        cap_max = _mc_max_keys()
        for raw_key in key_source:
            if capture is not None:
                capture.append(raw_key)
                if len(capture) > cap_max:
                    capture = None
            key = decode_dir_object(raw_key)
            if delimiter:
                rest = key[len(prefix) :]
                di = rest.find(delimiter)
                if di >= 0:
                    cp = prefix + rest[: di + len(delimiter)]
                    if cp in seen_prefixes or cp <= marker:
                        continue
                    if full():
                        out.is_truncated = True
                        out.next_marker = last_emitted
                        return out
                    seen_prefixes.add(cp)
                    out.prefixes.append(cp)
                    last_emitted = cp
                    continue
            if include_versions:
                if key < marker:
                    continue
                try:
                    versions = es.list_object_versions(bucket, key)
                except (ObjectNotFound, QuorumError, VersionNotFound):
                    continue
                resume_skip = key == marker and bool(version_marker)
                for oi in versions:
                    if resume_skip:
                        # resume strictly after the version-id marker
                        if oi.version_id == version_marker:
                            resume_skip = False
                        continue
                    if key == marker and not version_marker:
                        continue  # whole key returned on a prior page
                    oi.name = key
                    if len(out.objects) >= max_keys:
                        out.is_truncated = True
                        out.next_marker = last_emitted
                        out.next_version_marker = last_vid
                        return out
                    out.objects.append(oi)
                    last_emitted = key
                    last_vid = oi.version_id
                continue
            if key <= marker:
                continue
            try:
                oi = es.get_object_info(bucket, raw_key)
            except (ObjectNotFound, QuorumError, VersionNotFound):
                continue  # dangling or delete-marked
            if full():
                out.is_truncated = True
                out.next_marker = last_emitted
                return out
            oi.name = key
            out.objects.append(oi)
            last_emitted = key
        if capture is not None:
            _mc_mem_store(es, bucket, prefix, capture, cap_seq0)
        return out

    sk: ShardedKeys | None = None
    if marker:
        # continuation page: resume the cached sharded key stream at the
        # marker (a bisect, not a scan) instead of re-walking every drive
        sk = _metacache_keys(es, bucket, prefix)
    else:
        # repeated first-page scan: a fresh prior walk serves in-memory
        sk = _mc_mem_lookup(es, bucket, prefix)
    if sk is not None:
        try:
            return _run(sk.iter_from(marker), None, 0)
        except MetacacheGone:
            # a lazily-persisted shard vanished under us: drop the entry
            # and serve this page from a fresh walk (source of truth)
            _mc_drop(es, bucket, prefix)
    capture: list[str] | None = None
    cap_seq0 = 0
    if not marker:
        # capture the walk; if this page consumes it COMPLETELY (no
        # truncation) the keys are the full prefix listing — cache
        # them for free so the next scan is zero-walk
        capture = []
        cap_seq0 = _mc_bucket_seq(bucket)
    return _run(_merged_keys(es, bucket, prefix), capture, cap_seq0)
