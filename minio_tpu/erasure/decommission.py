"""Pool decommission and rebalance.

Mirrors /root/reference/cmd/erasure-server-pool-decom.go and
-rebalance.go: decommission drains every object of a pool into the
remaining pools (walk + re-PUT + delete, checkpointed under .minio.sys so
a restart resumes); rebalance moves objects from over-full pools toward
the pool free-space average. Both run as background threads driven from
the admin API.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass

from ..storage.errors import StorageError
from .quorum import ErasureError

SYSTEM_BUCKET = ".minio.sys"


@dataclass
class DecomStatus:
    pool_index: int
    state: str = "idle"  # idle | draining | complete | failed | canceled
    objects_moved: int = 0
    failed: int = 0
    bytes_moved: int = 0
    last_object: str = ""
    started: float = 0.0
    finished: float = 0.0
    error: str = ""

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class PoolManager:
    """Decommission/rebalance controller over ServerPools."""

    def __init__(self, pools):
        self.pools = pools  # ServerPools
        self.decoms: dict[int, DecomStatus] = {}
        self._cancel: set[int] = set()
        self._mu = threading.Lock()
        self._rebalance_state: dict = {"state": "idle"}
        self._rebalance_stop = threading.Event()

    # -- persistence -------------------------------------------------------

    def _ckpt_key(self, idx: int) -> str:
        return f"pool-decom/{idx}.json"

    def _save(self, st: DecomStatus) -> None:
        try:
            self.pools.put_object(
                SYSTEM_BUCKET, self._ckpt_key(st.pool_index),
                json.dumps(st.to_dict()).encode(),
            )
        except Exception:  # noqa: BLE001
            pass

    def load_checkpoint(self, idx: int) -> DecomStatus | None:
        from .quorum import ObjectNotFound

        try:
            _, it = self.pools.get_object(SYSTEM_BUCKET, self._ckpt_key(idx))
            return DecomStatus(**json.loads(b"".join(it)))
        except ObjectNotFound:
            return None  # no checkpoint yet: fresh start
        except (ValueError, TypeError, KeyError):
            # corrupt checkpoint doc: restarting the copy sweep is safe
            # (copies are idempotent). Quorum/storage errors PROPAGATE —
            # the old broad except silently discarded real progress and
            # restarted the whole decommission whenever the system
            # bucket was briefly unreadable.
            return None

    # -- decommission ------------------------------------------------------

    def start_decommission(self, pool_index: int) -> DecomStatus:
        if len(self.pools.pools) < 2:
            raise ValueError("cannot decommission the only pool")
        if not 0 <= pool_index < len(self.pools.pools):
            raise ValueError("bad pool index")
        prev = self.load_checkpoint(pool_index)
        st = prev if prev and prev.state == "draining" else DecomStatus(pool_index)
        st.state = "draining"
        st.started = st.started or time.time()
        with self._mu:
            self.decoms[pool_index] = st
        threading.Thread(
            target=self._drain, args=(st,), daemon=True,
            name=f"decom-{pool_index}",
        ).start()
        return st

    def cancel_decommission(self, pool_index: int) -> None:
        # written from the admin handler context, read by the _drain
        # thread: set mutation rides the same lock as the decom table
        # (miniovet races pass)
        with self._mu:
            self._cancel.add(pool_index)

    def _cancelled(self, pool_index: int) -> bool:
        with self._mu:
            return pool_index in self._cancel

    def status(self, pool_index: int) -> DecomStatus | None:
        return self.decoms.get(pool_index) or self.load_checkpoint(pool_index)

    def _drain(self, st: DecomStatus) -> None:
        with self._bg_ctx():
            self._drain_inner(st)

    @staticmethod
    def _bg_ctx():
        # QoS: decommission re-PUTs whole objects — their stripe blocks
        # ride the TPU dispatcher's background lane (leftover batch
        # capacity only), never displacing foreground traffic
        from ..qos.context import background_context

        return background_context()

    def _drain_inner(self, st: DecomStatus) -> None:
        src = self.pools.pools[st.pool_index]
        others = [
            p for i, p in enumerate(self.pools.pools) if i != st.pool_index
        ]
        dst = others[0]
        try:
            for b in src.list_buckets():
                for raw in src.walk_objects(b.name):
                    if self._cancelled(st.pool_index):
                        st.state = "canceled"
                        self._save(st)
                        return
                    cursor = f"{b.name}/{raw}"
                    if st.last_object and cursor <= st.last_object:
                        continue
                    try:
                        oi, it = src.get_object(b.name, raw)
                        data = b"".join(it)
                        meta = dict(oi.user_defined)
                        meta["content-type"] = oi.content_type
                        meta["etag"] = oi.etag
                        dst.put_object(b.name, raw, data, user_defined=meta)
                        src.delete_object(b.name, raw)
                        st.objects_moved += 1
                        st.bytes_moved += len(data)
                    except Exception:  # noqa: BLE001
                        st.failed += 1
                    st.last_object = cursor
                    if st.objects_moved % 100 == 0:
                        self._save(st)
            st.state = "complete" if st.failed == 0 else "failed"
        except Exception as e:  # noqa: BLE001
            st.state = "failed"
            st.error = str(e)
        st.finished = time.time()
        self._save(st)

    # -- rebalance ---------------------------------------------------------

    def pool_usage(self) -> list[dict]:
        out = []
        for i, p in enumerate(self.pools.pools):
            total = free = 0
            for d in p.disks:
                try:
                    di = d.disk_info()
                    total += di.total
                    free += di.free
                except (StorageError, OSError):
                    pass  # offline drive: skip its capacity, keep the rest
            out.append(
                {"pool": i, "total": total, "free": free,
                 "usedPct": 0.0 if not total else round(100 * (1 - free / total), 2)}
            )
        return out

    def start_rebalance_continuous(self, threshold_pct: float = 5.0) -> dict:
        """Run rebalance passes until pool fill spread drops below the
        threshold (reference StartRebalance,
        cmd/erasure-server-pool-rebalance.go:936 — continuous with status,
        not a single pass)."""
        import threading as _threading

        if len(self.pools.pools) < 2:
            raise ValueError("rebalance needs multiple pools")
        with self._mu:  # concurrent POSTs must not start two movers
            if self._rebalance_state.get("state") == "running":
                return dict(self._rebalance_state)
            self._rebalance_stop.clear()
            self._rebalance_state = {
                "state": "running", "moved": 0, "passes": 0,
                "threshold_pct": threshold_pct,
            }

        def loop():
            with self._bg_ctx():
                self._rebalance_loop(threshold_pct)

        _threading.Thread(target=loop, daemon=True, name="rebalance").start()
        return dict(self._rebalance_state)

    def _rebalance_loop(self, threshold_pct: float) -> None:
        st = self._rebalance_state
        while not self._rebalance_stop.is_set():
            usage = self.pool_usage()
            spread = max(u["usedPct"] for u in usage) - min(
                u["usedPct"] for u in usage
            )
            st["spread_pct"] = round(spread, 2)
            if spread <= threshold_pct:
                st["state"] = "done"
                return
            try:
                out = self.start_rebalance(max_objects=200)
            except Exception as e:  # noqa: BLE001
                st["state"] = "failed"
                st["error"] = str(e)
                return
            st["moved"] += out.get("moved", 0)
            st["passes"] += 1
            if out.get("moved", 0) == 0:
                st["state"] = "done"  # nothing movable: converged
                return
        st["state"] = "stopped"

    def stop_rebalance(self) -> dict:
        self._rebalance_stop.set()
        return dict(self._rebalance_state)

    def rebalance_status(self) -> dict:
        return dict(self._rebalance_state)

    def start_rebalance(self, max_objects: int = 1000) -> dict:
        """Move objects from the fullest pool to the emptiest until counts
        are bounded (simplified fill-percent equalization)."""
        if len(self.pools.pools) < 2:
            raise ValueError("rebalance needs multiple pools")
        usage = self.pool_usage()
        src_i = max(range(len(usage)), key=lambda i: usage[i]["usedPct"])
        dst_i = min(range(len(usage)), key=lambda i: usage[i]["usedPct"])
        if src_i == dst_i:
            return {"moved": 0}
        src, dst = self.pools.pools[src_i], self.pools.pools[dst_i]
        moved = 0
        for b in src.list_buckets():
            for raw in src.walk_objects(b.name):
                if moved >= max_objects:
                    return {"moved": moved, "from": src_i, "to": dst_i}
                try:
                    oi, it = src.get_object(b.name, raw)
                    dst.put_object(
                        b.name, raw, b"".join(it),
                        user_defined={**oi.user_defined,
                                      "content-type": oi.content_type,
                                      "etag": oi.etag},
                    )
                    src.delete_object(b.name, raw)
                    moved += 1
                except (ErasureError, StorageError, OSError):
                    pass  # this object stays put; the next pass retries
        return {"moved": moved, "from": src_i, "to": dst_i}
