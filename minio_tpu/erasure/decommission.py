"""Pool decommission and rebalance.

Mirrors /root/reference/cmd/erasure-server-pool-decom.go and
-rebalance.go: decommission drains every object of a pool into the
remaining pools (walk + re-PUT + delete, checkpointed under .minio.sys so
a restart resumes); rebalance moves objects from over-full pools toward
the pool free-space average. Both run as background threads driven from
the admin API, on the QoS background lane (their re-PUT stripe blocks
ride leftover dispatcher capacity only).

Placement-aware (placement/policy.py): rebalance never drains a key off
the pool a ``pin`` rule binds it to, and moves mis-placed pinned keys TO
their pool. Decommission overrides pins — the pool is going away.

Both movers are a ``topology`` fault-injection boundary (``fail-move`` /
``partition`` / ``latency``, target-matched against ``pool-<idx>``), and
both report progress breadth: moved objects/bytes, failures, started/
updated timestamps, live throughput and a bytes-based ETA — surfaced via
admin status and the metrics-v3 ``/api/topology`` group.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from .. import fault, obs
from ..storage.errors import StorageError
from .quorum import ErasureError

SYSTEM_BUCKET = ".minio.sys"


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _check_move_fault(pool_idx: int) -> None:
    """The topology fault boundary: one check per object move. fail-move
    raises (the object stays put, the next pass retries); partition
    raises the storage-flavored error an unreachable source pool would;
    latency stalls the mover thread."""
    rule = fault.check("topology", target=f"pool-{pool_idx}", op="move")
    if rule is None:
        return
    fault.sleep_latency(rule)
    if rule.mode == "fail-move":
        raise ErasureError(f"injected topology fault: mover failed "
                           f"(rule {rule.rule_id})")
    if rule.mode == "partition":
        from ..storage.errors import DiskNotFound

        raise DiskNotFound(
            f"injected topology fault: pool-{pool_idx} partitioned "
            f"(rule {rule.rule_id})"
        )


def _exists(pool, bucket: str, raw: str) -> bool:
    from .quorum import ObjectNotFound, VersionNotFound

    try:
        pool.get_object_info(bucket, raw)
        return True
    except (ObjectNotFound, VersionNotFound):
        return False


@dataclass
class DecomStatus:
    pool_index: int
    state: str = "idle"  # idle | draining | complete | failed | canceled
    objects_moved: int = 0
    failed: int = 0
    bytes_moved: int = 0
    last_object: str = ""
    started: float = 0.0
    updated: float = 0.0
    finished: float = 0.0
    error: str = ""

    def to_persist(self) -> dict:
        """Checkpoint form: exactly the dataclass fields (the loader
        round-trips this through ``DecomStatus(**doc)``)."""
        return dict(self.__dict__)

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        # breadth aliases the admin/metrics surface documents
        d["objectsMoved"] = self.objects_moved
        d["bytesMoved"] = self.bytes_moved
        d["failedObjects"] = self.failed
        return d


class PoolManager:
    """Decommission/rebalance controller over ServerPools."""

    def __init__(self, pools):
        self.pools = pools  # ServerPools
        self.decoms: dict[int, DecomStatus] = {}
        self._cancel: set[int] = set()
        self._mu = threading.Lock()
        self._rebalance_state: dict = {"state": "idle"}
        self._rebalance_stop = threading.Event()
        self._active: set[int] = set()  # pools with a live drain thread
        # pool_data_usage_cached state (metrics scrape path): instance-
        # owned so a recycled id() can never serve another manager's view
        self._data_usage_at = 0.0
        self._data_usage: list[dict] = []

    # -- persistence -------------------------------------------------------

    def _ckpt_key(self, idx: int) -> str:
        return f"pool-decom/{idx}.json"

    def _save(self, st: DecomStatus) -> None:
        try:
            self.pools.put_object(
                SYSTEM_BUCKET, self._ckpt_key(st.pool_index),
                json.dumps(st.to_persist()).encode(),
            )
        except (ErasureError, StorageError, OSError):
            pass  # checkpoint is best-effort: a resumed drain re-copies
            # (idempotent); infra code bugs still propagate

    def load_checkpoint(self, idx: int) -> DecomStatus | None:
        from .quorum import ObjectNotFound

        try:
            _, it = self.pools.get_object(SYSTEM_BUCKET, self._ckpt_key(idx))
            doc = json.loads(b"".join(it))
            fields = DecomStatus.__dataclass_fields__
            return DecomStatus(
                **{k: v for k, v in doc.items() if k in fields}
            )
        except ObjectNotFound:
            return None  # no checkpoint yet: fresh start
        except (ValueError, TypeError, KeyError):
            # corrupt checkpoint doc: restarting the copy sweep is safe
            # (copies are idempotent). Quorum/storage errors PROPAGATE —
            # the old broad except silently discarded real progress and
            # restarted the whole decommission whenever the system
            # bucket was briefly unreadable.
            return None

    # -- decommission ------------------------------------------------------

    def start_decommission(self, pool_index: int) -> DecomStatus:
        if len(self.pools.pools) < 2:
            raise ValueError("cannot decommission the only pool")
        if not 0 <= pool_index < len(self.pools.pools):
            raise ValueError("bad pool index")
        prev = self.load_checkpoint(pool_index)
        st = prev if prev and prev.state == "draining" else DecomStatus(pool_index)
        st.state = "draining"
        st.started = st.started or time.time()
        with self._mu:
            if pool_index in self._active:
                # one mover per pool: a drain (possibly cancelling) is
                # still running — return ITS status; discarding the
                # cancel flag here would revive it mid-cancel
                return self.decoms.get(pool_index, st)
            # a prior FINISHED cancel must not instantly kill this restart
            self._cancel.discard(pool_index)
            self._active.add(pool_index)
            self.decoms[pool_index] = st
            # placement stops landing NEW objects here, or the drain
            # would chase live writes forever (stays excluded once
            # complete — the pool is awaiting removal)
            draining = getattr(self.pools, "draining", None)
            if draining is not None:
                draining.add(pool_index)
        threading.Thread(
            target=self._drain, args=(st,), daemon=True,
            name=f"decom-{pool_index}",
        ).start()
        return st

    def cancel_decommission(self, pool_index: int) -> None:
        # written from the admin handler context, read by the _drain
        # thread: set mutation rides the same lock as the decom table
        # (miniovet races pass)
        with self._mu:
            self._cancel.add(pool_index)
            draining = getattr(self.pools, "draining", None)
            if draining is not None:
                draining.discard(pool_index)  # takes new objects again

    def _cancelled(self, pool_index: int) -> bool:
        with self._mu:
            return pool_index in self._cancel

    def status(self, pool_index: int) -> DecomStatus | None:
        return self.decoms.get(pool_index) or self.load_checkpoint(pool_index)

    def decom_snapshot(self) -> dict[int, DecomStatus]:
        """In-memory decommission table only — the metrics scrape path
        must not pay a quorum checkpoint read per pool per scrape."""
        with self._mu:
            return dict(self.decoms)

    def reindex_after_remove(self, removed: int) -> None:
        """A pool was detached (placement.topology.remove_pool): indexes
        shifted, so the removed pool's decommission state — in memory
        AND the persisted checkpoints — must go, and the survivors'
        re-key. Without this, the stale 'complete' record would vouch
        for a LATER pool attached at the same index, letting
        ``pool/remove`` detach it undrained."""
        with self._mu:
            n_old = len(self.pools.pools) + 1  # pool count BEFORE removal
            old = dict(self.decoms)
            self.decoms = {}
            for i, st in old.items():
                if i == removed:
                    continue
                ni = i - 1 if i > removed else i
                st.pool_index = ni
                self.decoms[ni] = st
            self._cancel = {
                i - 1 if i > removed else i
                for i in self._cancel if i != removed
            }
            self._active = {
                i - 1 if i > removed else i
                for i in self._active if i != removed
            }
            survivors = list(self.decoms.values())
        for i in range(n_old):
            try:
                self.pools.delete_object(SYSTEM_BUCKET, self._ckpt_key(i))
            except (ErasureError, StorageError, OSError):
                pass  # no checkpoint for this index
        for st in survivors:
            self._save(st)

    def _drain(self, st: DecomStatus) -> None:
        try:
            with self._bg_ctx():
                self._drain_inner(st)
        finally:
            with self._mu:
                self._active.discard(st.pool_index)

    def _pinned(self, bucket: str, obj: str) -> int | None:
        """Pinned pool index for a key, None when unruled (or this store
        predates the placement engine — embedders, fixtures)."""
        pl = getattr(self.pools, "placement", None)
        return pl.pinned_pool(bucket, obj) if pl is not None else None

    @staticmethod
    def _move_object(src, dst, bucket: str, raw: str) -> int:
        """Move one object between pools under live traffic. Optimistic
        concurrency: after staging the copy in ``dst``, the source is
        re-checked — a writer that overwrote it mid-move wins, and the
        now-stale staged copy is withdrawn (the unguarded
        get→put→delete would have deleted the NEW version and kept the
        old copy: a lost update). Returns bytes moved (0 = withdrawn,
        the next pass sees the fresh version)."""
        from .quorum import ObjectNotFound, VersionNotFound

        oi, it = src.get_object(bucket, raw)
        data = b"".join(it)
        meta = dict(oi.user_defined)
        meta["content-type"] = oi.content_type
        meta["etag"] = oi.etag
        dst.put_object(bucket, raw, data, user_defined=meta)
        try:
            cur = src.get_object_info(bucket, raw)
            if (cur.etag, cur.mod_time) != (oi.etag, oi.mod_time):
                dst.delete_object(bucket, raw)  # raced: withdraw the copy
                return 0
        except (ObjectNotFound, VersionNotFound):
            dst.delete_object(bucket, raw)  # deleted mid-move: honor it
            return 0
        src.delete_object(bucket, raw)
        return len(data)

    @staticmethod
    def _bg_ctx():
        # QoS: decommission re-PUTs whole objects — their stripe blocks
        # ride the TPU dispatcher's background lane (leftover batch
        # capacity only), never displacing foreground traffic
        from ..qos.context import background_context

        return background_context()

    def _drain_inner(self, st: DecomStatus) -> None:
        src = self.pools.pools[st.pool_index]
        others = [
            p for i, p in enumerate(self.pools.pools) if i != st.pool_index
        ]
        def _dst_for(bucket: str, raw: str):
            # destination must not itself be draining (another
            # decommission's cursor may already have passed the keys
            # we'd hand it — they would detach with that pool);
            # re-checked per move since decoms can start concurrently.
            # Decommission overrides pins (the pool is going away) but
            # honors a pin pointing at a surviving, non-draining pool.
            draining = set(getattr(self.pools, "draining", ()) or ())
            pinned = self._pinned(bucket, raw)
            if (
                pinned is not None
                and pinned != st.pool_index
                and pinned < len(self.pools.pools)
                and pinned not in draining
            ):
                return self.pools.pools[pinned]
            live = [
                p for i, p in enumerate(self.pools.pools)
                if i != st.pool_index and i not in draining
            ]
            return live[0] if live else others[0]

        try:
            raced: list[tuple[str, str]] = []
            for b in src.list_buckets():
                for raw in src.walk_objects(b.name):
                    if self._cancelled(st.pool_index):
                        st.state = "canceled"
                        self._save(st)
                        return
                    cursor = f"{b.name}/{raw}"
                    if st.last_object and cursor <= st.last_object:
                        continue
                    try:
                        _check_move_fault(st.pool_index)
                        n = self._move_object(
                            src, _dst_for(b.name, raw), b.name, raw
                        )
                        if n > 0:
                            st.objects_moved += 1
                            st.bytes_moved += n
                        elif _exists(src, b.name, raw):
                            # a writer overwrote it mid-move: the fresh
                            # version still sits in src — retry below
                            raced.append((b.name, raw))
                    except Exception:  # noqa: BLE001
                        st.failed += 1
                    st.last_object = cursor
                    st.updated = time.time()
                    if st.objects_moved % 100 == 0:
                        self._save(st)
            # raced objects got overwritten while being moved; their
            # fresh versions still need draining (bounded retries — a
            # writer hot enough to win 5 straight rounds leaves the
            # drain "failed", never silently incomplete)
            for _ in range(5):
                if not raced:
                    break
                if self._cancelled(st.pool_index):
                    # an intentional cancel mid-retry is "canceled", not
                    # a spurious "failed" with leftover raced entries
                    st.state = "canceled"
                    self._save(st)
                    return
                still: list[tuple[str, str]] = []
                for bn, raw in raced:
                    try:
                        _check_move_fault(st.pool_index)
                        n = self._move_object(src, _dst_for(bn, raw), bn, raw)
                        if n > 0:
                            st.objects_moved += 1
                            st.bytes_moved += n
                        elif _exists(src, bn, raw):
                            still.append((bn, raw))
                    except Exception:  # noqa: BLE001
                        st.failed += 1
                raced = still
                st.updated = time.time()
            st.failed += len(raced)
            st.state = "complete" if st.failed == 0 else "failed"
        except Exception as e:  # noqa: BLE001
            st.state = "failed"
            st.error = str(e)
        st.updated = st.finished = time.time()
        self._save(st)
        from ..placement.policy import emit

        emit(obs.TYPE_REBALANCE, "decom.finish", pool=st.pool_index,
             state=st.state, objectsMoved=st.objects_moved,
             bytesMoved=st.bytes_moved, failedObjects=st.failed)

    # -- rebalance ---------------------------------------------------------

    def pool_usage(self) -> list[dict]:
        out = []
        for i, p in enumerate(self.pools.pools):
            total = free = 0
            for d in p.disks:
                try:
                    di = d.disk_info()
                    total += di.total
                    free += di.free
                except (StorageError, OSError):
                    pass  # offline drive: skip its capacity, keep the rest
            out.append(
                {"pool": i, "total": total, "free": free,
                 "usedPct": 0.0 if not total else round(100 * (1 - free / total), 2)}
            )
        return out

    def pool_data_usage(self) -> list[dict]:
        """Per-pool STORED object bytes/counts (listing walk + quorum
        size reads). Drive fill (``pool_usage``) is the production
        signal, but pools sharing one filesystem — dev boxes, CI — give
        every pool identical statvfs numbers; stored bytes always
        distinguish them, and on dedicated drives the two equalize the
        same way (fill = stored bytes + a constant). ``fillPct`` weights
        stored bytes by each pool's capacity."""
        out = []
        for i, p in enumerate(self.pools.pools):
            nbytes = nobj = 0
            total = 0
            for d in p.disks:
                try:
                    total += d.disk_info().total
                except (StorageError, OSError):
                    pass  # offline drive: skip its capacity
            try:
                for b in p.list_buckets():
                    for raw in p.walk_objects(b.name):
                        try:
                            nbytes += p.get_object_info(b.name, raw).size
                            nobj += 1
                        except (ErasureError, StorageError, OSError):
                            pass  # raced a delete/move: next pass recounts
            except (ErasureError, StorageError, OSError):
                pass  # pool mid-churn: partial view, next pass recounts
            out.append({
                "pool": i, "objects": nobj, "bytes": nbytes,
                "total": total,
                "fillPct": 0.0 if not total
                else round(100.0 * nbytes / total, 6),
            })
        return out

    def pool_data_usage_cached(self, ttl_s: float = 10.0) -> list[dict]:
        """``pool_data_usage`` behind a TTL: the metrics scrape path must
        not pay the O(objects) listing walk per scrape."""
        import time as _time

        now = _time.monotonic()
        with self._mu:
            if self._data_usage and now - self._data_usage_at <= ttl_s:
                return self._data_usage
        data = self.pool_data_usage()
        with self._mu:
            self._data_usage = data
            self._data_usage_at = now
        return data

    def start_rebalance_continuous(self, threshold_pct: float | None = None) -> dict:
        """Run rebalance passes until pool fill spread drops below the
        threshold (reference StartRebalance,
        cmd/erasure-server-pool-rebalance.go:936 — continuous with status,
        not a single pass)."""
        import threading as _threading

        if threshold_pct is None:
            threshold_pct = _float_env("MINIO_TPU_REBALANCE_THRESHOLD_PCT", 5.0)
        if len(self.pools.pools) < 2:
            raise ValueError("rebalance needs multiple pools")
        with self._mu:  # concurrent POSTs must not start two movers
            if self._rebalance_state.get("state") == "running":
                return dict(self._rebalance_state)
            self._rebalance_stop.clear()
            self._rebalance_state = {
                "state": "running", "moved": 0, "passes": 0,
                "moved_bytes": 0, "failed": 0, "skipped_pinned": 0,
                "threshold_pct": threshold_pct,
                "started": time.time(), "updated": time.time(),
                "throughput_mibps": 0.0, "eta_s": None,
            }

        def loop():
            with self._bg_ctx():
                self._rebalance_loop(threshold_pct)

        _threading.Thread(target=loop, daemon=True, name="rebalance").start()
        return dict(self._rebalance_state)

    @staticmethod
    def data_spread_pct(data: list[dict]) -> float:
        """Stored-byte imbalance: (max share − min share) × 100, where a
        pool's share is its fraction of all stored bytes. 0 = perfectly
        even, 100 = everything on one pool."""
        total = sum(u["bytes"] for u in data)
        if total <= 0 or len(data) < 2:
            return 0.0
        shares = [u["bytes"] / total for u in data]
        return 100.0 * (max(shares) - min(shares))

    @staticmethod
    def _excess_bytes(data: list[dict]) -> int:
        """Bytes sitting above the across-pool mean — what a perfect
        rebalance would still move (the ETA numerator)."""
        if not data:
            return 0
        mean = sum(u["bytes"] for u in data) / len(data)
        return int(sum(max(0.0, u["bytes"] - mean) for u in data))

    def _rebalance_progress_locked(self, st: dict, spread: float,
                                   excess: int) -> None:
        st["spread_pct"] = round(spread, 2)
        st["updated"] = time.time()
        elapsed = max(st["updated"] - st["started"], 1e-9)
        st["throughput_mibps"] = round(
            st["moved_bytes"] / (1 << 20) / elapsed, 3
        )
        bps = st["moved_bytes"] / elapsed
        st["eta_s"] = round(excess / bps, 1) if bps > 0 else None

    def _rebalance_loop(self, threshold_pct: float) -> None:
        from ..placement.policy import emit

        pause = _float_env("MINIO_TPU_REBALANCE_PAUSE_S", 0.0)
        batch = int(_float_env("MINIO_TPU_REBALANCE_BATCH", 200))
        stalled = 0  # consecutive passes that moved nothing
        while not self._rebalance_stop.is_set():
            draining = set(getattr(self.pools, "draining", ()) or ())
            full = self.pool_data_usage()  # ONE walk per iteration:
            # start_rebalance reuses it for src/dst selection below
            data = [u for u in full if u["pool"] not in draining]
            spread = self.data_spread_pct(data)
            excess = self._excess_bytes(data)
            with self._mu:
                st = self._rebalance_state
                self._rebalance_progress_locked(st, spread, excess)
                converged = spread <= threshold_pct
                if converged:
                    st["state"] = "done"
                snap = dict(st)
            if converged:
                emit(obs.TYPE_REBALANCE, "rebalance.finish",
                     state="done", **_progress_fields(snap))
                return
            try:
                out = self.start_rebalance(
                    max_objects=max(batch, 1), usage=full
                )
            except Exception as e:  # noqa: BLE001
                with self._mu:
                    st = self._rebalance_state
                    st["state"] = "failed"
                    st["error"] = str(e)
                emit(obs.TYPE_REBALANCE, "rebalance.finish",
                     state="failed", error=str(e))
                return
            with self._mu:
                st = self._rebalance_state
                st["moved"] += out.get("moved", 0)
                st["moved_bytes"] += out.get("moved_bytes", 0)
                st["failed"] += out.get("failed", 0)
                st["skipped_pinned"] += out.get("skipped_pinned", 0)
                st["passes"] += 1
                self._rebalance_progress_locked(st, spread, excess)
                stalled = 0 if out.get("moved", 0) > 0 else stalled + 1
                dry = out.get("moved", 0) == 0 and out.get("failed", 0) == 0
                wedged = stalled >= 3  # failures only, no progress: a
                # persistently unmovable object must not busy-loop the
                # mover forever (failed passes get retried twice)
                if dry:
                    st["state"] = "done"  # nothing movable: converged
                elif wedged:
                    st["state"] = "failed"
                    st["error"] = (
                        f"no progress after {stalled} passes "
                        "(persistent move failures)"
                    )
                snap = dict(st)
            emit(obs.TYPE_REBALANCE, "rebalance.pass",
                 **{**_progress_fields(snap),
                    "from": out.get("from"), "to": out.get("to")})
            if dry or wedged:
                emit(obs.TYPE_REBALANCE, "rebalance.finish",
                     state=snap["state"], **_progress_fields(snap))
                return
            # pace between passes: a pass that moved nothing (all moves
            # failing) must not re-walk the namespace back-to-back
            sleep_for = max(pause, 0.2 if out.get("moved", 0) == 0 else 0.0)
            if sleep_for > 0:
                # miniovet: ignore[blocking] -- dedicated rebalance
                # daemon thread pacing itself between passes
                time.sleep(sleep_for)
        with self._mu:
            self._rebalance_state["state"] = "stopped"

    def stop_rebalance(self) -> dict:
        self._rebalance_stop.set()
        with self._mu:
            return dict(self._rebalance_state)

    def rebalance_status(self) -> dict:
        with self._mu:
            return dict(self._rebalance_state)

    def start_rebalance(self, max_objects: int = 1000,
                        usage: list[dict] | None = None) -> dict:
        """One rebalance pass: move objects off the fullest pool (most
        stored bytes) toward the emptiest until ``max_objects`` are
        bounded. Placement-aware: keys pinned to the source pool stay
        put; keys pinned ELSEWHERE move to their pinned pool rather than
        the emptiest. ``usage`` lets the continuous loop share its
        already-computed walk instead of paying a second one."""
        if len(self.pools.pools) < 2:
            raise ValueError("rebalance needs multiple pools")
        if usage is None or len(usage) != len(self.pools.pools):
            usage = self.pool_data_usage()
        # pools under decommission belong to the drain: rebalance must
        # neither fill them (objects landing behind the drain cursor
        # would be detached with the pool) nor race it as a source
        draining = set(getattr(self.pools, "draining", ()) or ())
        live = [i for i in range(len(usage)) if i not in draining]
        if len(live) < 2:
            return {"moved": 0, "moved_bytes": 0, "failed": 0,
                    "skipped_pinned": 0}
        src_i = max(live, key=lambda i: usage[i]["bytes"])
        dst_i = min(live, key=lambda i: usage[i]["bytes"])
        if src_i == dst_i or usage[src_i]["bytes"] == usage[dst_i]["bytes"]:
            return {"moved": 0, "moved_bytes": 0, "failed": 0,
                    "skipped_pinned": 0}
        src, dst = self.pools.pools[src_i], self.pools.pools[dst_i]
        # never move past the midpoint of the byte gap: an unbounded
        # pass would overshoot and the next pass would slosh data back
        target_bytes = (usage[src_i]["bytes"] - usage[dst_i]["bytes"]) / 2
        moved = moved_bytes = failed = skipped_pinned = 0

        def out() -> dict:
            return {"moved": moved, "moved_bytes": moved_bytes,
                    "failed": failed, "skipped_pinned": skipped_pinned,
                    "from": src_i, "to": dst_i}

        for b in src.list_buckets():
            for raw in src.walk_objects(b.name):
                if moved >= max_objects or moved_bytes >= target_bytes:
                    return out()
                pinned = self._pinned(b.name, raw)
                if pinned == src_i:
                    skipped_pinned += 1
                    continue  # never drain a pinned key off its pool
                to = (
                    self.pools.pools[pinned]
                    if pinned is not None
                    and pinned < len(self.pools.pools)
                    and pinned not in draining
                    else dst
                )
                if to is src:
                    skipped_pinned += 1
                    continue
                try:
                    _check_move_fault(src_i)
                    n = self._move_object(src, to, b.name, raw)
                    if n > 0:
                        moved += 1
                        moved_bytes += n
                except (ErasureError, StorageError, OSError):
                    failed += 1  # stays put; the next pass retries
        return out()


def _progress_fields(st: dict) -> dict:
    return {
        "moved": st.get("moved", 0),
        "movedBytes": st.get("moved_bytes", 0),
        "failedObjects": st.get("failed", 0),
        "passes": st.get("passes", 0),
        "spreadPct": st.get("spread_pct", 0.0),
        "throughputMiBps": st.get("throughput_mibps", 0.0),
        "etaS": st.get("eta_s"),
    }
