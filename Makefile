# Developer entry points. `make check` is the static gate every PR must
# pass (tier-1 enforces the same thing via tests/test_analysis.py).

PY ?= python

.PHONY: check check-clean test docs bench-smoke diag-smoke

# whole-program static analysis (per-file rules + interprocedural
# passes) with the content-hash incremental cache: warm runs re-parse
# only changed files (timings on stderr). `make check-clean` busts it.
check:
	$(PY) -m minio_tpu.analysis minio_tpu/ --strict --cache --jobs 2

check-clean:
	$(PY) -m minio_tpu.analysis --clean-cache
	$(PY) -m minio_tpu.analysis minio_tpu/ --strict --cache --jobs 2

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

docs:
	$(PY) -m minio_tpu.analysis --gen-config-docs docs/CONFIG.md
	$(PY) -m minio_tpu.analysis minio_tpu/ --cache --gen-lock-order docs/LOCK_ORDER.md
	$(PY) -m minio_tpu.analysis minio_tpu/ --cache --gen-concurrency docs/CONCURRENCY.md
	$(PY) -m minio_tpu.analysis minio_tpu/ --cache --gen-resources docs/RESOURCES.md
	$(PY) -m minio_tpu.analysis minio_tpu/ --cache --gen-surface docs/SURFACE.md

# harness-stays-runnable gate: the closed-loop load harness end to end
# (worker pool, mixed zipf traffic, heal flood, QoS guard metrics) in
# seconds — full runs write BENCH json, this just proves it still works.
# Then every named workload profile at toy scale, each with its real
# gates armed (a missing gate series fails the run, never passes it) —
# --all includes repair-degraded-storm, the seeded drive-failure +
# straggler storm with verifying traffic and the windowed-vs-serial
# repair A/B.
bench-smoke:
	MINIO_TPU_BACKEND=numpy $(PY) benchmarks/bench_load.py --quick
	MINIO_TPU_BACKEND=numpy $(PY) -m benchmarks.scenarios --all --quick

# self-measurement plane end to end vs a live 2-worker pool: quick
# object/drive/net speedtests + healthinfo (json & zip) with zero
# request errors, and every /api/diag series the static surface
# manifest declares present in the live scrape.
diag-smoke:
	MINIO_TPU_BACKEND=numpy $(PY) scripts/diag_smoke.py
