# Developer entry points. `make check` is the static gate every PR must
# pass (tier-1 enforces the same thing via tests/test_analysis.py).

PY ?= python

.PHONY: check test docs

check:
	$(PY) -m minio_tpu.analysis minio_tpu/ --strict

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

docs:
	$(PY) -m minio_tpu.analysis --gen-config-docs docs/CONFIG.md
